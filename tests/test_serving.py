"""Continuous-batching serving engine (reference: the serving loop
around AnalysisPredictor / ``Predictor.run``'s fused_multi_transformer
decode HOT LOOP — SURVEY.md §2.6/§3.5): the greedy arm is oracle-tested
BIT-EXACT against per-request sequential ``generate_on_device`` under
ragged arrivals with slot reuse, plus pool-allocator lifecycle
(free-list reuse after retirement, exhaustion refusal, fragmentation
counters), scheduler admission gating, and the registered
``serving_decode_step`` analysis budget (zero involuntary remat, zero
host syncs in the jitted quantum, KV pool leaves donated).

The SPECULATIVE serving arm (ISSUE 3) gets the same treatment: the
greedy drafter/verifier round is bit-exact vs sequential generate with
an arbitrary independent draft (exactness by construction), the
rejection-sampling arm replays the plain sampling engine bit-for-bit
when draft == target on fixed seeds, eos/max-new retirement composes
with variable per-round yield, admission accounts for the draft pool,
and the ``speculative_verify_step`` budget pins the one-dispatch
round.

The FRONT DOOR's engine tier (ISSUE 7): the preemption correctness
oracle — a preempted-then-resumed request's stream is BIT-EXACT vs an
undisturbed run in both the greedy and fixed-seed sampling arms, with
TTFT observed exactly once despite the re-prefill — plus per-request
temperature threading (a uniform-temps front-door engine replays the
engine-wide sampling engine bit-for-bit), host-side stop rules,
refcount-safe pool release (shared blocks survive one holder's
eviction), a 100-round ragged preempt/resume leak hunt at the
scheduler level, priority admission ordering, and the
``serving_frontdoor_step`` budget + golden pinning the
per-slot-temperature quantum variant."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp import PagedKVCachePool
from paddle_tpu.nlp.generation import (
    generate_on_device, speculative_generate,
)
from paddle_tpu.serving import Request, Scheduler, SchedulerConfig
from paddle_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


@pytest.fixture(scope="module")
def tiny_draft():
    """An INDEPENDENT (random-init, shallower) draft: near-floor
    acceptance, which is exactly the adversarial case for greedy
    exactness-by-construction."""
    paddle.seed(11)
    draft = LlamaForCausalLM(
        LlamaConfig.tiny(tensor_parallel=False, num_hidden_layers=1))
    draft.eval()
    return draft


def _oracle_row(model, prompt, max_new, eos_token_id=None):
    """Sequential single-request reference; returns the generated ids
    TRUNCATED at eos (generate_on_device pads the tail with eos, the
    engine retires the slot instead)."""
    out = generate_on_device(model, paddle.to_tensor(prompt[None, :]),
                             max_new_tokens=max_new,
                             eos_token_id=eos_token_id)
    row = np.asarray(out._value)[0]
    gen = row[prompt.shape[0]:]
    if eos_token_id is not None:
        hits = np.nonzero(gen == eos_token_id)[0]
        if hits.size:
            gen = gen[:hits[0] + 1]
    return np.concatenate([prompt, gen])


# ------------------------------------------------ engine vs sequential
def test_engine_greedy_oracle_ragged(tiny_model):
    """The correctness oracle: 5 ragged requests over 3 slots (so
    retirement + slot/block reuse happens mid-run), chunked prefill
    interleaved with decode — outputs bit-exact vs per-request
    sequential generate. The same run carries the ISSUE 7 preemption
    oracle (request 0 is evicted mid-decode and resumes by re-prefill
    of prompt+tokens: its stream must STILL be bit-exact, with TTFT
    observed exactly once despite the re-prefill) and the host-side
    stop-token rule (request 4 stops at a token its own oracle row
    predicts — truncate-at-stop, finish_reason "stop")."""
    cfg, model = tiny_model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
               for n in (5, 9, 3, 12, 7)]
    max_new = [6, 4, 8, 5, 7]
    wants = [_oracle_row(model, p, mn)
             for p, mn in zip(prompts, max_new)]
    # request 4 additionally carries a stop rule on its 3rd generated
    # token; its expected output is the oracle row truncated there
    stop_tok = int(wants[4][prompts[4].shape[0] + 2])
    wants[4] = wants[4][:prompts[4].shape[0] + 3]
    engine = ServingEngine(model, num_slots=3, block_size=4,
                           prefill_chunk=4, decode_quantum=3)
    reqs = [engine.submit(p, max_new_tokens=mn,
                          stop_token_ids=[stop_tok] if i == 4 else None)
            for i, (p, mn) in enumerate(zip(prompts, max_new))]
    # evict request 0 mid-decode: blocks back to the pool, requeued at
    # the head of its class, resumed via re-prefill
    while len(reqs[0].tokens) < 2:
        engine.step()
    assert not reqs[0].finished
    engine.preempt(reqs[0])
    assert reqs[0].slot is None and reqs[0].prefill_pos == 0
    assert reqs[0].prefill_target == prompts[0].shape[0] + len(
        reqs[0].tokens)
    done = engine.run()
    assert len(done) == len(reqs)
    assert engine.scheduler.finished_total == len(reqs)
    for req, want in zip(reqs, wants):
        np.testing.assert_array_equal(engine.output_tokens(req), want)
    assert reqs[4].finish_reason == "stop"
    # TTFT observed exactly once per request despite req0's re-prefill
    assert engine.obs.registry.get(
        "serving_ttft_seconds").count() == len(reqs)
    st = engine.engine_stats()
    assert st["preempted"] == 1 and st["resumed"] == 1
    assert engine.obs.registry.get(
        "serving_tokens_recomputed_total").value() >= 2
    # every request retired -> all its blocks are back on the free list
    stats = engine.pool.fragmentation_stats()
    assert stats["blocks_in_use"] == 1  # only the engine scratch block
    assert stats["blocks_freed_total"] > 0
    assert engine.engine_stats()["decode_quanta"] > 0


def test_engine_eos_retirement(tiny_model):
    """Device-computed eos masks retire slots mid-quantum; outputs stay
    bit-exact (truncated-at-eos convention) and blocks free."""
    cfg, model = tiny_model
    rng = np.random.RandomState(1)
    probe = rng.randint(1, cfg.vocab_size, 6).astype(np.int32)
    row = _oracle_row(model, probe, 10)
    eos = int(row[6 + 3])  # the 4th greedy token becomes "eos"
    prompts = [probe,
               rng.randint(1, cfg.vocab_size, 4).astype(np.int32),
               rng.randint(1, cfg.vocab_size, 8).astype(np.int32)]
    engine = ServingEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=3, decode_quantum=4,
                           eos_token_id=eos)
    reqs = [engine.submit(p, max_new_tokens=10) for p in prompts]
    engine.run()
    assert reqs[0].finish_reason == "eos"
    for req, p in zip(reqs, prompts):
        np.testing.assert_array_equal(
            engine.output_tokens(req),
            _oracle_row(model, p, 10, eos_token_id=eos))
    assert engine.pool.fragmentation_stats()["blocks_in_use"] == 1


def test_engine_sampling_smoke(tiny_model, sampling_prompts,
                               plain_sampling_outputs):
    """The sampling arm drives to completion with per-request seeds and
    in-vocab tokens (selection math shared with generation's
    _filter_logits; distributional parity is its own test tier). The
    run itself is the module-shared plain_sampling_outputs fixture —
    the same run is the speculative parity test's oracle."""
    cfg, _ = tiny_model
    assert len(plain_sampling_outputs) == 3
    for out, p in zip(plain_sampling_outputs, sampling_prompts):
        gen = out[p.shape[0]:]
        assert gen.shape[0] == 5
        assert all(0 <= t < cfg.vocab_size for t in gen)


def test_engine_rejects_oversize_and_bad_strategy(tiny_model):
    cfg, model = tiny_model
    engine = ServingEngine(model, num_slots=2, block_size=4,
                           max_context=32)
    with pytest.raises(ValueError, match="max_context"):
        engine.submit(np.arange(1, 30, dtype=np.int32),
                      max_new_tokens=8)
    with pytest.raises(ValueError, match="greedy|sampling"):
        ServingEngine(model, decode_strategy="beam")


# ------------------------------------------------ preemption oracle
def test_preemption_and_temperature_sampling_bit_exact(
        tiny_model, sampling_prompts, plain_sampling_outputs):
    """ISSUE 7 oracle, fixed-seed sampling arm — one front-door engine
    (per_request_sampling=True) proves two bit-exactness claims against
    the module-shared plain sampling run at once: (a) per-request
    TEMPERATURE threads through the per-slot temps input of the
    front-door quantum variant (every request passes the temperature
    the engine-wide fixture used — uniform temps must replay it
    bit-for-bit), and (b) the fold_in(key, n_emitted) token-stream
    discipline survives EVICTION — a preempted request re-prefills and
    continues the SAME sample stream, with TTFT observed once."""
    cfg, model = tiny_model
    engine = ServingEngine(model, decode_quantum=3,
                           per_request_sampling=True, **_SAMPLING_KW)
    reqs = [engine.submit(p, max_new_tokens=5, seed=i,
                          temperature=_SAMPLING_KW["temperature"])
            for i, p in enumerate(sampling_prompts)]
    while len(reqs[0].tokens) < 2:
        engine.step()
    assert not reqs[0].finished
    engine.preempt(reqs[0])
    engine.run()
    for req, want in zip(reqs, plain_sampling_outputs):
        np.testing.assert_array_equal(engine.output_tokens(req), want)
    assert engine.scheduler.preempted_total == 1
    assert engine.scheduler.resumed_total == 1
    assert engine.obs.registry.get("serving_ttft_seconds").count() == 3


def test_per_request_param_validation(tiny_model):
    """Temperature needs the front-door quantum variant; the variant
    needs the sampling strategy; stop rules are pure host checks."""
    cfg, model = tiny_model
    engine = ServingEngine(model, num_slots=2, block_size=4)
    with pytest.raises(ValueError, match="per_request_sampling"):
        engine.submit(np.arange(1, 5, dtype=np.int32), temperature=0.7)
    with pytest.raises(ValueError, match="sampling"):
        ServingEngine(model, per_request_sampling=True)
    with pytest.raises(NotImplementedError, match="spec_draft"):
        ServingEngine(model, decode_strategy="sampling",
                      per_request_sampling=True, spec_draft=model)
    # stop-sequence rule, host-side (no engine run needed)
    req = Request(np.arange(1, 5), max_new_tokens=10,
                  stop_sequences=[[7, 8]])
    for t in (5, 7, 8):
        req.record(t)
    assert req.finished and req.finish_reason == "stop"
    assert req.tokens == [5, 7, 8]


# ------------------------------------------------ speculative arm
def test_spec_engine_greedy_oracle_ragged_eos(tiny_model, tiny_draft):
    """ISSUE 3 acceptance: the greedy speculative round is EXACT BY
    CONSTRUCTION — an arbitrary independent (near-floor-acceptance)
    draft leaves the served outputs bit-identical to target-only
    sequential generate, under ragged arrivals over fewer slots
    (retirement + slot/block reuse mid-run) with device-computed eos
    truncating the round's variable yield in-graph. Prompt shapes
    match the plain-engine eos test so the sequential oracle compiles
    are cache hits."""
    cfg, model = tiny_model
    rng = np.random.RandomState(1)
    probe = rng.randint(1, cfg.vocab_size, 6).astype(np.int32)
    row = _oracle_row(model, probe, 10)
    eos = int(row[6 + 3])  # the 4th greedy token becomes "eos"
    prompts = [probe,
               rng.randint(1, cfg.vocab_size, 4).astype(np.int32),
               rng.randint(1, cfg.vocab_size, 8).astype(np.int32)]
    engine = ServingEngine(model, spec_draft=tiny_draft, spec_gamma=2,
                           num_slots=2, block_size=4, prefill_chunk=3,
                           eos_token_id=eos)
    reqs = [engine.submit(p, max_new_tokens=10) for p in prompts]
    done = engine.run()
    assert len(done) == len(reqs)
    assert reqs[0].finish_reason == "eos"
    for req, p in zip(reqs, prompts):
        np.testing.assert_array_equal(
            engine.output_tokens(req),
            _oracle_row(model, p, 10, eos_token_id=eos))
    st = engine.engine_stats()
    assert st["spec_rounds"] > 0
    assert st["spec_proposed"] >= st["spec_accepted"] >= 0
    # retirement drains BOTH pools back to their scratch block
    assert engine.pool.fragmentation_stats()["blocks_in_use"] == 1
    assert engine.d_pool.fragmentation_stats()["blocks_in_use"] == 1


_SAMPLING_KW = dict(num_slots=2, block_size=4, prefill_chunk=4,
                    decode_strategy="sampling", top_k=8,
                    temperature=0.9)


@pytest.fixture(scope="module")
def sampling_prompts(tiny_model):
    cfg, _ = tiny_model
    rng = np.random.RandomState(2)
    return [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
            for n in (5, 7, 3)]


@pytest.fixture(scope="module")
def plain_sampling_outputs(tiny_model, sampling_prompts):
    """One PLAIN sampling-engine run (max_new 5, per-request seed i)
    shared by the smoke test and the speculative parity oracle — one
    compile, one execution."""
    _, model = tiny_model
    engine = ServingEngine(model, decode_quantum=3, **_SAMPLING_KW)
    reqs = [engine.submit(p, max_new_tokens=5, seed=i)
            for i, p in enumerate(sampling_prompts)]
    engine.run()
    assert len(engine.completed) == len(reqs)
    return [engine.output_tokens(r) for r in reqs]


def test_spec_engine_sampling_parity_fixed_seeds(tiny_model,
                                                 sampling_prompts,
                                                 plain_sampling_outputs):
    """Rejection-sampling arm with draft == target: q == p, so every
    proposal accepts, and the fold_in(key, n_emitted) token-stream
    discipline makes the speculative engine replay the PLAIN sampling
    engine's output bit-for-bit on fixed seeds — the deterministic
    oracle the sampling arm has (the greedy arm's is sequential
    generate)."""
    cfg, model = tiny_model
    spec = ServingEngine(model, spec_draft=model, spec_gamma=2,
                         **_SAMPLING_KW)
    reqs = [spec.submit(p, max_new_tokens=5, seed=i)
            for i, p in enumerate(sampling_prompts)]
    spec.run()
    for req, want in zip(reqs, plain_sampling_outputs):
        np.testing.assert_array_equal(spec.output_tokens(req), want)
    st = spec.engine_stats()
    assert st["spec_proposed"] > 0
    assert st["spec_accepted"] == st["spec_proposed"]  # q == p


@pytest.mark.slow
def test_speculative_generate_facade(tiny_model, tiny_draft):
    """nlp.generation.speculative_generate: batch rows ride serving
    slots; greedy output equals target-only generate row-for-row."""
    cfg, model = tiny_model
    rng = np.random.RandomState(0)
    prompts = np.stack([rng.randint(1, cfg.vocab_size, 5)
                        .astype(np.int32) for _ in range(2)])
    out, rate = speculative_generate(model, tiny_draft, prompts,
                                     max_new_tokens=6, gamma=3)
    out = np.asarray(out._value)
    for i in range(2):
        np.testing.assert_array_equal(out[i],
                                      _oracle_row(model, prompts[i], 6))
    assert 0.0 <= rate <= 1.0


def test_spec_engine_rejects_bad_draft(tiny_model, tiny_draft):
    cfg, model = tiny_model
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(model, spec_draft=LlamaForCausalLM(
            LlamaConfig.tiny(tensor_parallel=False, vocab_size=64)))
    with pytest.raises(ValueError, match="spec_gamma"):
        ServingEngine(model, spec_draft=tiny_draft, spec_gamma=0)


# ------------------------------------------------ pool lifecycle
def _pool(num_blocks=8, bs=4):
    return PagedKVCachePool(num_blocks=num_blocks, block_size=bs,
                            num_kv_heads=2, head_dim=8,
                            dtype=jnp.float32)


def test_pool_free_list_reuse_after_retirement():
    """A retiring sequence's blocks go straight to the next admission
    (LIFO free list — immediate reuse, no compaction pass)."""
    pool = _pool()
    t_a = list(pool.ensure("a", 9))   # 3 blocks
    pool.ensure("b", 4)               # 1 block
    assert pool.blocks_in_use == 4
    pool.free("a")
    assert pool.free_blocks == 7
    assert pool.seq_len("a") == 0
    t_c = list(pool.ensure("c", 12))  # 3 blocks: exactly a's, reused
    assert set(t_c) == set(t_a)
    assert pool.fragmentation_stats()["blocks_freed_total"] == 3


def test_pool_exhaustion_refusal():
    pool = _pool(num_blocks=4)
    pool.ensure("a", 12)  # 3 blocks
    assert not pool.can_allocate(8)
    assert pool.can_allocate(4)
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.ensure("b", 8)
    pool.free("a")
    assert pool.can_allocate(8)
    pool.ensure("b", 8)  # now fits


def test_pool_fragmentation_counters():
    """Only INTERNAL fragmentation exists (tail waste in each last
    block); utilization is live tokens over allocated capacity."""
    pool = _pool(bs=4)
    pool.ensure("a", 5)  # 2 blocks, 3 tail-waste tokens
    pool.ensure("b", 4)  # 1 block, 0 waste
    s = pool.fragmentation_stats()
    assert s["blocks_in_use"] == 3
    assert s["live_tokens"] == 9
    assert s["tail_waste_tokens"] == 3
    assert s["utilization"] == pytest.approx(9 / 12)
    assert s["peak_blocks_in_use"] == 3
    pool.free("a")
    s2 = pool.fragmentation_stats()
    assert s2["peak_blocks_in_use"] == 3  # high-water mark sticks
    assert s2["utilization"] == pytest.approx(1.0)


def test_pool_trim_releases_tail_blocks():
    """trim() is the rollback/realloc path: shrink a live sequence,
    tail blocks return to the free list, table order preserved."""
    pool = _pool(bs=4)
    table = list(pool.ensure("a", 15))  # 4 blocks
    released = pool.trim("a", 6)        # keep 2 blocks
    assert released == table[2:]
    assert pool.seq_len("a") == 6
    assert pool.free_blocks == 6
    assert pool.trim("a", 100) == []    # growing is ensure()'s job
    assert pool.seq_len("a") == 6
    assert pool.trim("missing", 3) == []


def test_pool_refcount_share_release():
    """Refcount-safe release (the eviction/prefix-sharing primitive):
    a block shared by two holders survives the first free and only
    returns to the free list — and counts as freed — when the LAST
    holder releases it; double-release of an untracked block raises."""
    pool = _pool(num_blocks=8, bs=4)
    t_a = list(pool.ensure("a", 8))       # 2 blocks
    t_b = pool.share("a", "b")            # aliases, refcount 2 each
    assert t_b == t_a
    assert pool.blocks_in_use == 2
    pool.free("a")
    # b still holds the blocks: nothing returned to the free list
    assert pool.blocks_in_use == 2
    assert pool.fragmentation_stats()["blocks_freed_total"] == 0
    pool.free("b")
    assert pool.blocks_in_use == 0
    assert pool.fragmentation_stats()["blocks_freed_total"] == 2
    pool.ensure("c", 4)
    with pytest.raises(ValueError, match="already exists"):
        pool.share("a", "c")
    with pytest.raises(KeyError):
        pool.share("missing", "d")
    with pytest.raises(RuntimeError, match="double free"):
        pool._release([t_a[0]])
    # trim decrements too: a shared tail block is not freed early
    pool2 = _pool(num_blocks=8, bs=4)
    pool2.ensure("x", 8)
    pool2.share("x", "y")
    pool2.trim("x", 4)                    # x drops its tail block
    assert pool2.blocks_in_use == 2       # y still maps it
    pool2.free("y")
    assert pool2.blocks_in_use == 1       # x's head block remains


def test_preemption_no_block_leak_100_ragged_rounds():
    """ISSUE 7 acceptance: 100 rounds of ragged admit / partial-ensure
    / preempt / resume / retire churn at the scheduler+pool level —
    blocks_in_use must return to zero every round and the free list
    must be whole at the end (an off-by-one in eviction release would
    leak monotonically and fail fast here)."""
    rng = np.random.RandomState(0)
    pool = _pool(num_blocks=24, bs=4)
    sched = Scheduler(SchedulerConfig(num_slots=4), pool)
    for round_i in range(100):
        reqs = [Request(np.arange(1, 1 + rng.randint(2, 12)),
                        max_new_tokens=int(rng.randint(1, 12)),
                        priority=int(rng.randint(0, 3)))
                for _ in range(rng.randint(1, 6))]
        for r in reqs:
            sched.submit(r)
        live = sched.try_admit()
        # simulate partial prefill/decode pool growth per live request
        for r in live:
            grown = min(r.prompt_len + rng.randint(0, r.max_new_tokens
                                                   + 1),
                        r.prompt_len + r.max_new_tokens)
            pool.ensure(r.req_id, grown)
        # preempt a random subset, resume them, then retire everything
        for r in list(live):
            if rng.rand() < 0.5:
                sched.preempt(r)
        sched.try_admit()  # resumed + any still-waiting requests
        for r in [x for x in sched.slots if x is not None]:
            pool.ensure(r.req_id, r.prompt_len + r.max_new_tokens)
            r.finished = True
            sched.retire(r)
        # anything left waiting (slots exhausted) drains next round;
        # flush it now so every round starts clean
        while sched.waiting:
            for r in sched.try_admit():
                r.finished = True
                sched.retire(r)
        assert pool.blocks_in_use == 0, f"leak at round {round_i}"
        assert sched.reserved_blocks == 0
    assert pool.free_blocks == pool.num_blocks
    assert sched.preempted_total > 0 and sched.resumed_total > 0


def test_scheduler_priority_admission_and_preempt_requeue():
    """Priority-then-FIFO admission: the highest class admits first
    (stable within a class), a preempted request re-enters at the head
    of its class, and ``can_admit`` reports slot/block pressure the
    preemption policy keys on."""
    pool = _pool(num_blocks=12, bs=4)
    sched = Scheduler(SchedulerConfig(num_slots=2), pool)
    lo = sched.submit(Request(np.arange(1, 5), max_new_tokens=4,
                              priority=0))
    mid = sched.submit(Request(np.arange(1, 5), max_new_tokens=4,
                               priority=1))
    hi = sched.submit(Request(np.arange(1, 5), max_new_tokens=4,
                              priority=2))
    assert sched.next_waiting() is hi
    assert sched.try_admit() == [hi, mid]     # strict priority order
    assert lo.slot is None
    assert not sched.can_admit(lo)            # both slots taken
    sched.preempt(mid)
    assert sched.preempted_total == 1
    assert mid.prefill_target == mid.prompt_len  # no tokens yet
    # mid (priority 1) outranks lo in the queue again; lo keeps
    # waiting for a slot
    assert sched.next_waiting() is mid
    assert sched.can_admit(mid)
    assert sched.try_admit() == [mid]
    assert sched.resumed_total == 1
    hi.finished = True
    sched.retire(hi)
    assert sched.try_admit() == [lo]
    assert sched.admitted_total == 3          # resume is not a new admit


# ------------------------------------------------ scheduler accounting
def test_scheduler_admission_gating():
    """Admission is gated on WORST-CASE demand (prompt + max_new) so the
    pool can never exhaust mid-decode; FIFO order holds, and a request
    that can never fit raises instead of wedging the queue."""
    pool = _pool(num_blocks=6, bs=4)
    sched = Scheduler(SchedulerConfig(num_slots=4), pool)
    a = sched.submit(Request(np.arange(1, 9), max_new_tokens=8))   # 4 blk
    b = sched.submit(Request(np.arange(1, 5), max_new_tokens=4))   # 2 blk
    c = sched.submit(Request(np.arange(1, 5), max_new_tokens=4))   # 2 blk
    admitted = sched.try_admit()
    assert admitted == [a, b]          # c: 4+2+2 > 6 blocks
    assert sched.reserved_blocks == 6
    assert c.slot is None
    # retiring a releases its reservation; c admits into the freed slot
    a.finished = True
    sched.retire(a)
    assert sched.try_admit() == [c]
    with pytest.raises(ValueError, match="blocks"):
        sched.submit(Request(np.arange(1, 20), max_new_tokens=20))
        sched.try_admit()


def test_scheduler_companion_pool_and_margin():
    """Speculative admission accounts for the DRAFT pool too: capacity
    gates on the tightest pool, demand carries the γ token margin (the
    verify step's worst-case writes), and retirement frees blocks in
    every pool."""
    pool = _pool(num_blocks=8, bs=4)
    d_pool = _pool(num_blocks=4, bs=4)  # the tighter pool gates
    sched = Scheduler(SchedulerConfig(num_slots=4), pool,
                      companion_pools=[d_pool], token_margin=3)
    a = sched.submit(Request(np.arange(1, 6), max_new_tokens=8))
    # demand = ceil((5 + 8 + 3) / 4) = 4 blocks — fills d_pool exactly
    assert sched.try_admit() == [a]
    assert sched.reserved_blocks == 4
    b = sched.submit(Request(np.arange(1, 3), max_new_tokens=2))
    assert sched.try_admit() == []      # draft-pool capacity exhausted
    pool.ensure(a.req_id, 5)
    d_pool.ensure(a.req_id, 5)
    a.finished = True
    sched.retire(a)                      # frees BOTH pools
    assert pool.blocks_in_use == 0 and d_pool.blocks_in_use == 0
    assert sched.try_admit() == [b]
    with pytest.raises(ValueError, match="block_size"):
        Scheduler(SchedulerConfig(), pool,
                  companion_pools=[_pool(bs=8)])


# ------------------------------------------------ the analysis budget
def test_serving_decode_step_budget():
    """The machine-checked single-dispatch invariant (ISSUE 2
    acceptance): the EXACT quantum the engine dispatches has zero
    involuntary remat, zero host callbacks/transfers, no collectives,
    bf16 stays bf16, every KV pool leaf is donated, and temp/peak-live
    memory stays inside the budget — then the full fingerprint must
    match the checked-in golden (the ISSUE 4 drift gate; same audited
    report, no extra compile)."""
    from paddle_tpu import analysis

    report = analysis.run_recipe("serving_decode_step")
    assert len(report.remat_events) == 0
    assert report.host_sync is not None and report.host_sync.count == 0
    assert report.total_collectives == 0
    assert report.donation.undonated() == []
    assert report.memory.temp_bytes is not None
    analysis.check_recipe_fingerprint("serving_decode_step", report)


def test_serving_frontdoor_step_budget():
    """ISSUE 7 acceptance: the front-door quantum variant (per-slot
    temperature input, sampling selection in-graph), built through an
    engine that just served a priority preemption + resume with the
    FULL policy/obs tier attached, still has zero host callbacks, zero
    involuntary remat, no collectives, every KV pool leaf donated —
    and its own golden fingerprint matches, while the plain engines'
    goldens are untouched (their tests above compare against the same
    checked-in files as before). The whole policy layer provably never
    enters the compiled program."""
    from paddle_tpu import analysis

    recipe = analysis.build_recipe("serving_frontdoor_step")
    try:
        report = recipe.check()
        # the audited engine really went through the front door's
        # overload path before the audit
        assert recipe.engine.scheduler.preempted_total == 1
        assert recipe.engine.scheduler.resumed_total == 1
        assert len(report.remat_events) == 0
        assert report.host_sync is not None \
            and report.host_sync.count == 0
        assert report.total_collectives == 0
        assert report.donation.undonated() == []
        analysis.check_recipe_fingerprint("serving_frontdoor_step",
                                          report)
    finally:
        recipe.close()


def test_speculative_verify_step_budget():
    """ISSUE 3 acceptance: the EXACT speculative round the engine
    dispatches — draft-γ scan + target verify + in-graph acceptance —
    has zero involuntary remat, zero host callbacks/transfers, no
    collectives, bf16 stays bf16, and BOTH pools' KV leaves (2L_target
    + 2L_draft) are donated."""
    from paddle_tpu import analysis

    report = analysis.run_recipe("speculative_verify_step")
    assert len(report.remat_events) == 0
    assert report.host_sync is not None and report.host_sync.count == 0
    assert report.total_collectives == 0
    assert report.donation.undonated() == []
    assert report.donation.n_donatable == 6  # 2*2 target + 2*1 draft
    # the liveness walk must see the donation actually saving HBM:
    # both pools roll in-place rather than double-buffering
    assert report.memory.liveness.donation_savings_bytes > 0
    analysis.check_recipe_fingerprint("speculative_verify_step", report)
