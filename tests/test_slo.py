"""paddle_tpu.obs operability tier (ISSUE 6): SLO burn-rate health,
the live HTTP exporter, and the per-request flight recorder.

Three tiers, mirroring test_obs.py: pure-host unit tests (burn-rate
math against hand-computed windows including the empty-window and
clock-skew edges, health-state ordering, flight-recorder bounded
buffers and JSONL schema round-trip, exporter e2e scrapes over a
localhost ephemeral port with ``prometheus_from_snapshot`` parity and
``/healthz`` status codes on BOTH sides of a threshold), one
engine-integration fixture (a single tiny engine run shared by every
engine test — quantum compiles are expensive) asserting
``engine.health()`` and full-lifecycle anomaly journals, and the
offline CLI paths (``slo --in``, ``watch --in``). The
graph-can't-change half is asserted where the fingerprints live: the
``serving_decode_step`` / ``speculative_verify_step`` recipes now
build their engines with ``slo=True, flight=True``."""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.obs import (
    CRITICAL, OK, WARN, FlightRecorder, MetricsExporter,
    MetricsRegistry, SLO, SLOSet, ServingObs, default_serving_slos,
    load_flight_records, prometheus_from_snapshot, render_dashboard,
    state_of, validate_flight_records, worst_state,
)
from paddle_tpu.serving.scheduler import Request


# ------------------------------------------------- health-state order
def test_health_state_total_order():
    assert OK < WARN < CRITICAL
    assert CRITICAL > WARN > OK
    # compares against string names too (report consumers)
    assert CRITICAL > "warn" and WARN >= "warn" and OK == "ok"
    assert str(WARN) == "warn"
    assert state_of("critical") is CRITICAL
    assert worst_state([]) is OK
    assert worst_state(["ok", "critical", "warn"]) is CRITICAL
    with pytest.raises(ValueError, match="unknown health state"):
        state_of("meh")


# ------------------------------------------------- burn-rate math
def test_burn_rate_hand_computed_windows():
    """Window membership, bad fractions and burn rates checked against
    hand-arithmetic: budget 0.1, fast window 2/4 bad -> burn 5.0, slow
    window 2/10 bad -> burn 2.0; both >= warn(2) but fast < crit(8)
    -> WARN."""
    slo = SLO("ttft_p95", "ttft_seconds", threshold=0.1, target=0.9,
              fast_window=300.0, slow_window=3600.0,
              warn_burn=2.0, critical_burn=8.0)
    now = 10_000.0
    series = {"ttft_seconds": (
        # inside the fast window: 4 samples, 2 over the 0.1s threshold
        [(now - 10, 0.05), (now - 20, 0.2), (now - 100, 0.3),
         (now - 300, 0.01)]            # age == window is IN (<=)
        # slow-window-only: 6 good samples
        + [(now - 1000 - i, 0.05) for i in range(6)]
        # outside both windows: terrible, and correctly ignored
        + [(now - 4000, 99.0)])}
    rep = slo.evaluate(series, now=now)
    fast, slow = rep["windows"]["fast"], rep["windows"]["slow"]
    assert (fast["n"], fast["bad"]) == (4, 2)
    assert fast["bad_fraction"] == pytest.approx(0.5)
    assert fast["burn_rate"] == pytest.approx(5.0)
    assert (slow["n"], slow["bad"]) == (10, 2)
    assert slow["burn_rate"] == pytest.approx(2.0)
    assert rep["state"] == "warn"
    assert rep["budget"] == pytest.approx(0.1)


def test_multiwindow_gating_suppresses_spike_and_stale():
    """A short burst (fast hot, slow cold) and a long-ago incident
    (slow hot, fast recovered) both read OK — the SRE rationale for
    requiring BOTH windows to burn."""
    slo = SLO("x", "ttft_seconds", threshold=0.1, target=0.9,
              fast_window=10.0, slow_window=100.0,
              warn_burn=2.0, critical_burn=8.0)
    now = 1000.0
    spike = {"ttft_seconds": [(now - 1, 1.0)] * 3
             + [(now - 50 - 0.1 * i, 0.01) for i in range(97)]}
    rep = slo.evaluate(spike, now=now)
    assert rep["windows"]["fast"]["burn_rate"] >= 8.0
    assert rep["windows"]["slow"]["burn_rate"] < 2.0
    assert rep["state"] == "ok"
    stale = {"ttft_seconds": [(now - 50, 1.0)] * 30
             + [(now - 1 - 0.1 * i, 0.01) for i in range(30)]}
    rep = slo.evaluate(stale, now=now)
    assert rep["windows"]["slow"]["burn_rate"] >= 2.0
    assert rep["state"] == "ok"
    # both windows burning critical -> CRITICAL
    rep = slo.evaluate({"ttft_seconds": [(now - 1, 1.0)] * 5}, now=now)
    assert rep["state"] == "critical"


def test_empty_window_burns_nothing():
    """No traffic is not an outage: missing series, empty series, and
    all-samples-aged-out all read n=0, burn 0.0, OK."""
    slo = SLO("x", "e2e_latency_seconds", threshold=1.0, target=0.99)
    for series, now in (({}, 5.0),
                        ({"e2e_latency_seconds": []}, 5.0),
                        ({"e2e_latency_seconds": [(0.0, 99.0)]}, 1e7)):
        rep = slo.evaluate(series, now=now)
        assert rep["state"] == "ok"
        for w in rep["windows"].values():
            assert w["n"] == 0 and w["burn_rate"] == 0.0


def test_clock_skew_future_samples_count_as_now():
    """A sample stamped AFTER the evaluation clock (skew across
    threads/hosts) is clamped to age 0 and counted in every window —
    never silently dropped."""
    slo = SLO("x", "ttft_seconds", threshold=0.1, target=0.9,
              warn_burn=2.0, critical_burn=8.0)
    now = 100.0
    rep = slo.evaluate({"ttft_seconds": [(now + 50.0, 5.0)]}, now=now)
    for w in rep["windows"].values():
        assert w["n"] == 1 and w["bad"] == 1
    assert rep["state"] == "critical"


def test_rate_objective_over_request_outcomes():
    """error/shed rate: the series already records good(0)/bad(1), so
    the bad fraction IS the rate; burn = rate / error budget."""
    slo = SLO("err", "request_outcomes", target=0.99,
              warn_burn=2.0, critical_burn=10.0)
    now = 50.0
    pts = [(now - i, 1.0 if i < 2 else 0.0) for i in range(10)]
    rep = slo.evaluate({"request_outcomes": pts}, now=now)
    fast = rep["windows"]["fast"]
    assert (fast["n"], fast["bad"]) == (10, 2)
    assert fast["burn_rate"] == pytest.approx(0.2 / 0.01)
    assert rep["state"] == "critical"
    # rate signals take no threshold
    with pytest.raises(ValueError, match="no threshold"):
        SLO("err", "request_outcomes", threshold=1.0)


def test_slo_validation_is_loud():
    with pytest.raises(ValueError, match="unknown signal"):
        SLO("x", "nope")
    with pytest.raises(ValueError, match="positive threshold"):
        SLO("x", "ttft_seconds")
    with pytest.raises(ValueError, match="target must be"):
        SLO("x", "ttft_seconds", threshold=1.0, target=1.0)
    with pytest.raises(ValueError, match="fast_window < slow_window"):
        SLO("x", "ttft_seconds", threshold=1.0, fast_window=100,
            slow_window=100)
    with pytest.raises(ValueError, match="warn_burn <= critical_burn"):
        SLO("x", "ttft_seconds", threshold=1.0, warn_burn=5,
            critical_burn=2)
    with pytest.raises(ValueError, match="duplicate SLO name"):
        SLOSet([SLO("a", "ttft_seconds", threshold=1.0),
                SLO("a", "e2e_latency_seconds", threshold=1.0)])


def test_default_slo_set_and_threshold_lookup():
    s = SLOSet()
    assert {o.name for o in s} == {"ttft_p95", "inter_token_p99",
                                   "e2e_p99", "error_rate"}
    assert s.threshold("ttft_seconds") == 0.5
    assert s.threshold("e2e_latency_seconds") == 30.0
    assert s.threshold("request_outcomes") is None  # rate: no latency
    rep = s.evaluate({}, now=1.0)
    assert rep["version"] == 1 and rep["state"] == "ok"
    assert len(rep["objectives"]) == 4
    # the report is pure JSON
    assert json.loads(json.dumps(rep)) == rep


# ------------------------------------------------- obs sample series
def _req(rid, prompt=3, max_new=4, arrival=0.0):
    return Request(np.arange(1, prompt + 1, dtype=np.int32),
                   max_new_tokens=max_new, req_id=rid,
                   arrival_time=arrival)


def test_serving_obs_sample_series_feed_the_slos():
    """The hooks append the (t, value) samples the burn-rate windows
    read — TTFT/e2e/inter-token per request, outcome 0.0 for a good
    ending and 1.0 for a shed — and SLOSet.evaluate consumes the
    ServingObs object directly."""
    obs = ServingObs()
    r = _req("r0", arrival=10.0)
    obs.on_submit(r)
    r.slot = 0
    obs.on_admit(r, 10.5)
    r.first_token_time = 11.0
    obs.on_first_token(r, 11.0)
    r.record(5, None)
    r.record(6, None)
    r.finish_time = 12.0
    r.finished, r.finish_reason = True, "length"
    obs.on_retire(r, 12.0)
    ts = obs.timeseries()
    assert ts["ttft_seconds"] == [(11.0, pytest.approx(1.0))]
    assert ts["e2e_latency_seconds"] == [(12.0, pytest.approx(2.0))]
    assert ts["inter_token_seconds"] == [(12.0, pytest.approx(1.0))]
    assert ts["request_outcomes"] == [(12.0, 0.0)]
    shed = _req("r1", arrival=12.5)
    obs.on_shed(shed, 13.0)
    assert obs.timeseries()["request_outcomes"][-1] == (13.0, 1.0)
    assert obs.registry.get(
        "serving_requests_shed_total").value() == 1
    # burn-rate evaluation straight off the obs object: 1 bad of 2
    # outcomes -> burn 50x the 1% budget in both windows -> critical
    rep = SLOSet().evaluate(obs, now=13.0)
    err = [o for o in rep["objectives"] if o["name"] == "error_rate"]
    assert err[0]["state"] == "critical"
    # the snapshot is the offline `slo --in` format
    snap = obs.series_snapshot(now=13.0)
    assert snap["version"] == 1 and snap["now"] == 13.0
    assert snap["series"]["ttft_seconds"] == [[11.0, 1.0]]
    # reset clears every surface
    obs.reset()
    assert all(not v for v in obs.timeseries().values())
    assert obs.registry.get("serving_requests_shed_total").value() == 0


def test_registry_and_histogram_reset():
    """ISSUE 6 satellite: the explicit bench-warmup reset — series
    cleared, instruments (identity + buckets) kept."""
    r = MetricsRegistry()
    c = r.counter("c")
    c.inc(3, route="x")
    g = r.gauge("g")
    g.set(2)
    h = r.histogram("h", buckets=(1.0, 2.0))
    h.observe(0.5)
    h.observe(9.0)
    h.reset()
    assert h.count() == 0 and h.sum() == 0.0
    assert h.bucket_counts() == [0, 0, 0]
    r.reset()
    assert c.value(route="x") == 0.0 and g.value() == 0.0
    assert r.counter("c") is c  # still registered, same instrument
    h.observe(1.5)
    assert h.count() == 1 and h.buckets == (1.0, 2.0)


# ------------------------------------------------- flight recorder
def test_flight_journal_lifecycle_and_anomaly_capture(tmp_path):
    fr = FlightRecorder(ttft_threshold=0.5, e2e_threshold=2.0)
    ok = _req("a")
    fr.on_submit(ok, 0.0)
    ok.slot = 0
    fr.on_admit(ok, 0.1, queue_wait=0.1, blocks_reserved=2,
                pool_free_blocks=6, pool_blocks_in_use=2)
    fr.on_prefill_chunk(ok, 0.2, 3, 3)
    fr.on_first_token(ok, 0.3, 0.3)
    fr.on_quantum_tokens(ok, 0.5, 2)
    ok.tokens = [1, 2]
    fr.on_retire(ok, 0.6, ttft=0.3, e2e=0.6, reason="length")
    # under both thresholds: journal released, nothing captured
    assert fr.anomalies == [] and fr.live_count == 0
    assert fr.retired_total == 1 and fr.captured_total == 0

    bad = _req("b")
    fr.on_submit(bad, 0.0)
    bad.slot = 1
    fr.on_admit(bad, 0.1)
    fr.on_prefill_chunk(bad, 0.8, 3, 3)
    fr.on_first_token(bad, 0.9, 0.9)
    fr.on_spec_round(bad, 2.5, proposed=4, accepted=3, emitted=4)
    bad.tokens = [1, 2, 3, 4]
    fr.on_retire(bad, 3.0, ttft=0.9, e2e=3.0, reason="length")
    recs = fr.records()  # schema-validates
    assert len(recs) == 1 and recs[0]["req_id"] == "b"
    assert set(recs[0]["anomaly"]["signals"]) == {
        "ttft_seconds", "e2e_latency_seconds"}
    sig = recs[0]["anomaly"]["signals"]["ttft_seconds"]
    assert sig["value"] == pytest.approx(0.9)
    assert sig["threshold"] == pytest.approx(0.5)
    assert [e["kind"] for e in recs[0]["events"]] == [
        "submit", "admit", "prefill_chunk", "first_token",
        "spec_round", "retire"]
    assert recs[0]["events"][4]["accepted"] == 3
    # JSONL round-trip through disk
    path = str(tmp_path / "anomalies.jsonl")
    fr.save(path)
    assert load_flight_records(path) == recs


def test_flight_bounded_buffers_count_drops():
    fr = FlightRecorder(e2e_threshold=0.0, max_live=2, max_events=3,
                        max_anomalies=1)
    a, b, c = _req("a"), _req("b"), _req("c")
    fr.on_submit(a, 0.0)
    fr.on_submit(b, 0.0)
    fr.on_submit(c, 0.0)  # live table full -> rides unjournaled
    assert fr.live_count == 2 and fr.dropped_requests == 1
    for r in (a, b):
        r.slot = 0
        fr.on_admit(r, 0.1)
        fr.on_prefill_chunk(r, 0.2, 3, 3)      # journal now full (3)
        fr.on_first_token(r, 0.3, 0.3)         # dropped, counted
        fr.on_quantum_tokens(r, 0.4, 1)        # dropped, counted
    fr.on_retire(a, 1.0, ttft=0.3, e2e=1.0, reason="length")
    fr.on_retire(b, 1.0, ttft=0.3, e2e=1.0, reason="length")
    fr.on_retire(c, 1.0, ttft=0.3, e2e=1.0, reason="length")  # no-op
    st = fr.stats()
    assert st["anomalies"] == 1          # buffer bound
    assert st["dropped_anomalies"] == 1  # b's capture found it full
    assert st["captured_total"] == 2 and st["retired_total"] == 3
    recs = fr.records()
    # the retire event still lands (it pops the journal regardless),
    # so the journal stays schema-valid: submit ... retire with the
    # mid-flight overflow counted
    assert recs[0]["dropped_events"] == 2
    assert recs[0]["events"][-1]["kind"] == "retire"


def test_flight_thresholds_come_from_slo_set():
    fr = FlightRecorder(slo=SLOSet())
    assert fr.ttft_threshold == 0.5 and fr.e2e_threshold == 30.0
    # explicit override wins
    assert FlightRecorder(slo=SLOSet(),
                          ttft_threshold=9.9).ttft_threshold == 9.9
    # no SLO, no overrides: nothing ever triggers
    fr = FlightRecorder()
    r = _req("a")
    fr.on_submit(r, 0.0)
    fr.on_retire(r, 1e9, ttft=1e8, e2e=1e9, reason="length")
    assert fr.records() == []


def test_flight_shed_always_captures():
    fr = FlightRecorder()  # even with no thresholds: shedding IS an
    r = _req("s")          # anomaly
    fr.on_submit(r, 0.0)
    fr.on_shed(r, 0.1, reason="pool_pressure")
    recs = fr.records()
    assert [e["kind"] for e in recs[0]["events"]] == ["submit", "shed"]
    assert "shed" in recs[0]["anomaly"]["signals"]
    assert recs[0]["anomaly"]["reason"] == "pool_pressure"


def test_validate_flight_records_is_loud():
    good = {
        "req_id": "a", "prompt_len": 3, "max_new_tokens": 4,
        "dropped_events": 0,
        "anomaly": {"t": 1.0, "reason": "length", "tokens": 2,
                    "signals": {"ttft_seconds":
                                {"value": 1.0, "threshold": 0.5}}},
        "events": [{"t": 0.0, "kind": "submit"},
                   {"t": 1.0, "kind": "retire"}],
    }
    validate_flight_records([good])
    for mutate, msg in (
            (lambda r: r.pop("anomaly"), "missing 'anomaly'"),
            (lambda r: r["anomaly"].update(signals={}),
             "non-empty dict"),
            (lambda r: r["events"].__setitem__(
                0, {"t": 0.0, "kind": "warp"}), "kind must be"),
            (lambda r: r["events"].reverse(), "time-ordered"),
            (lambda r: r["events"].pop(), "end at retire"),
            (lambda r: r.update(dropped_events=-1), "non-negative"),
    ):
        rec = json.loads(json.dumps(good))
        mutate(rec)
        with pytest.raises(ValueError, match=msg):
            validate_flight_records([rec])


# ------------------------------------------------- exporter e2e
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:       # 4xx/5xx still carry a
        return e.code, e.read().decode()      # body we assert on


def test_exporter_scrape_and_healthz_threshold_sides():
    """e2e over localhost on an ephemeral port: /metrics text parses
    back byte-identical via prometheus_from_snapshot, /healthz flips
    200 ok -> 503 critical as the SAME objective crosses its
    threshold, /slo carries the full burn-rate report, /anomalies
    streams the flight dumps, unknown routes 404."""
    registry = MetricsRegistry()
    registry.counter("serving_requests_finished_total",
                     "requests retired").inc(2)
    registry.histogram("serving_ttft_seconds",
                       buckets=(0.01, 0.1)).observe(0.05)
    slos = SLOSet([SLO("ttft_p95", "ttft_seconds", threshold=0.1,
                       target=0.9, warn_burn=2.0, critical_burn=8.0)])
    now = time.perf_counter()
    good = {"ttft_seconds": [(now, 0.01)] * 8}
    bad = {"ttft_seconds": [(now, 5.0)] * 8}
    flight = FlightRecorder(e2e_threshold=0.0)
    r = _req("slow")
    flight.on_submit(r, 0.0)
    flight.on_retire(r, 1.0, ttft=0.5, e2e=1.0, reason="length")

    exporter = MetricsExporter(registry, slos=slos, obs=good,
                               flight=flight).start()
    try:
        assert exporter.port != 0  # ephemeral port resolved
        status, prom = _get(exporter.url("/metrics"))
        assert status == 200
        assert prom == registry.prometheus() \
            == prometheus_from_snapshot(registry.snapshot())
        assert "serving_ttft_seconds_bucket" in prom

        status, body = _get(exporter.url("/healthz"))
        assert status == 200
        assert json.loads(body) == {
            "state": "ok", "objectives": {"ttft_p95": "ok"}}

        status, body = _get(exporter.url("/snapshot"))
        assert status == 200 and json.loads(body) == registry.snapshot()

        status, body = _get(exporter.url("/slo"))
        report = json.loads(body)
        assert status == 200 and report["state"] == "ok"
        assert report["objectives"][0]["windows"]["fast"]["n"] == 8

        status, body = _get(exporter.url("/anomalies"))
        assert status == 200
        recs = [json.loads(ln) for ln in body.splitlines()]
        assert validate_flight_records(recs)[0]["req_id"] == "slow"

        status, body = _get(exporter.url("/nope"))
        assert status == 404 and "/healthz" in body

        # the other side of the threshold: same objective, now
        # burning >= critical in both windows -> 503 + critical
        exporter.obs = bad
        status, body = _get(exporter.url("/healthz"))
        assert status == 503
        assert json.loads(body) == {
            "state": "critical", "objectives": {"ttft_p95": "critical"}}
    finally:
        exporter.stop()
    with pytest.raises(Exception):  # really stopped
        urllib.request.urlopen(exporter.url("/metrics"), timeout=1)


def test_exporter_without_slos_or_flight():
    exporter = MetricsExporter(MetricsRegistry()).start()
    try:
        status, body = _get(exporter.url("/healthz"))
        assert status == 200 and json.loads(body)["state"] == "ok"
        status, _ = _get(exporter.url("/anomalies"))
        assert status == 404
    finally:
        exporter.stop()


def test_render_dashboard_frame():
    registry = MetricsRegistry()
    registry.counter("serving_requests_submitted_total").inc(5)
    registry.counter("serving_requests_finished_total").inc(4)
    registry.counter("serving_tokens_emitted_total").inc(37)
    registry.gauge("serving_tokens_per_second_window").set(123.4)
    registry.gauge("serving_pool_blocks_in_use").set(6, pool="target")
    registry.gauge("serving_pool_free_blocks").set(2, pool="target")
    registry.gauge("serving_pool_utilization").set(0.75, pool="target")
    h = registry.histogram("serving_ttft_seconds", buckets=(0.01, 0.1))
    h.observe(0.05)
    now = time.perf_counter()
    report = SLOSet().evaluate({"ttft_seconds": [(now, 0.01)]}, now=now)
    text = render_dashboard(registry.snapshot(), report)
    assert "[OK] ok" in text
    assert "ttft_p95" in text and "burn fast" in text
    assert "submitted       5" in text
    assert "123.4 tok/s" in text
    assert "pool[target]" in text and "util  75.0%" in text
    # renders without a report too (watch --in with no --slo-in)
    assert "health: [?] n/a" in render_dashboard(registry.snapshot())


# ------------------------------------------------- engine integration
@pytest.fixture(scope="module")
def slo_engine():
    """ONE tiny engine run shared by the engine-tier tests (the
    quantum compile is the expensive part): SLOs attached, flight
    recorder with an impossible TTFT trigger so EVERY request is a
    threshold-crossing anomaly."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine

    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=4, decode_quantum=3, slo=True,
                           flight=FlightRecorder(ttft_threshold=1e-9))
    rng = np.random.RandomState(3)
    for n, mn in ((5, 4), (7, 3), (3, 5)):
        engine.submit(rng.randint(1, cfg.vocab_size, n)
                      .astype(np.int32), max_new_tokens=mn)
    done = engine.run()
    return engine, done


def test_engine_health_both_sides_of_threshold(slo_engine):
    """engine.health() produces the stock report, and explicit
    lenient/impossible objective sets over the SAME run read ok /
    critical — synthetic traffic on both sides of an SLO threshold."""
    engine, done = slo_engine
    rep = engine.health()
    assert rep["state"] in ("ok", "warn", "critical")
    assert {o["name"] for o in rep["objectives"]} == {
        "ttft_p95", "inter_token_p99", "e2e_p99", "error_rate"}
    # every request produced exactly one ttft/e2e sample
    ttft = [o for o in rep["objectives"] if o["name"] == "ttft_p95"][0]
    assert ttft["windows"]["fast"]["n"] == len(done)
    lenient = SLOSet(default_serving_slos(
        ttft_p95_s=1e9, inter_token_p99_s=1e9, e2e_p99_s=1e9))
    tight = SLOSet(default_serving_slos(
        ttft_p95_s=1e-9, inter_token_p99_s=1e-9, e2e_p99_s=1e-9))
    assert lenient.evaluate(engine.obs)["state"] == "ok"
    assert tight.evaluate(engine.obs)["state"] == "critical"
    # an engine without slo= refuses loudly
    with pytest.raises(ValueError, match="without slo="):
        from paddle_tpu.serving import ServingEngine

        ServingEngine.health(
            type("E", (), {"slo": None})())


def test_engine_anomaly_dump_full_lifecycle(slo_engine, tmp_path):
    """Every request crossed the forced TTFT trigger: each dump is a
    schema-valid journal carrying the FULL lifecycle, in order, with
    pool/block context on the admit event."""
    engine, done = slo_engine
    recs = engine.flight.records()  # schema-validates
    assert len(recs) == len(done)
    assert {r["req_id"] for r in recs} == {q.req_id for q in done}
    for rec, req in zip(
            sorted(recs, key=lambda r: r["req_id"]),
            sorted(done, key=lambda q: str(q.req_id))):
        kinds = [e["kind"] for e in rec["events"]]
        assert kinds[0] == "submit" and kinds[-1] == "retire"
        assert "admit" in kinds and "first_token" in kinds
        assert "prefill_chunk" in kinds
        admit = rec["events"][kinds.index("admit")]
        assert admit["pool_free_blocks"] is not None
        assert admit["queue_wait_s"] >= 0
        assert "ttft_seconds" in rec["anomaly"]["signals"]
        retire = rec["events"][-1]
        assert retire["tokens"] == len(req.tokens)
        # decode tokens are journaled (quantum yields and/or the
        # mixed-step rows); prompt never is
        assert rec["prompt_len"] == req.prompt_len
    path = str(tmp_path / "dump.jsonl")
    engine.flight.save(path)
    assert load_flight_records(path) == recs
    assert engine.flight.stats()["live"] == 0


def test_engine_exporter_serves_live_state(slo_engine):
    """MetricsExporter.for_engine wires every surface: the /healthz
    status code agrees with the /slo state, /metrics carries the
    engine's real histograms, /anomalies the real dumps."""
    engine, done = slo_engine
    exporter = MetricsExporter.for_engine(engine).start()
    try:
        status, prom = _get(exporter.url("/metrics"))
        assert status == 200
        assert f"serving_ttft_seconds_count {len(done)}" in prom
        status, body = _get(exporter.url("/slo"))
        state = json.loads(body)["state"]
        hz_status, hz_body = _get(exporter.url("/healthz"))
        assert json.loads(hz_body)["state"] == state
        assert hz_status == (503 if state == "critical" else 200)
        status, body = _get(exporter.url("/anomalies"))
        assert len(body.splitlines()) == len(done)
    finally:
        exporter.stop()


# ------------------------------------------------- offline CLI paths
def test_slo_cli_offline_snapshot(tmp_path, capsys):
    """`slo --in` evaluates a saved series snapshot without an engine
    (tier-1-cheap), and --fail-on turns the state into an exit code."""
    from paddle_tpu.obs.__main__ import main

    now = 500.0
    snap = {"version": 1, "now": now,
            "series": {"ttft_seconds": [[now - 1.0, 0.001]] * 4,
                       "request_outcomes": [[now - 1.0, 0.0]] * 4}}
    path = str(tmp_path / "series.json")
    with open(path, "w") as f:
        json.dump(snap, f)
    assert main(["slo", "--in", path]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["state"] == "ok" and len(rep["objectives"]) == 4
    assert main(["slo", "--in", path, "--fail-on", "warn"]) == 0
    # flip the traffic to the bad side: critical + fail-on trips
    snap["series"]["ttft_seconds"] = [[now - 1.0, 9.0]] * 4
    with open(path, "w") as f:
        json.dump(snap, f)
    assert main(["slo", "--in", path, "--fail-on", "critical"]) == 1
    capsys.readouterr()
    # not a series snapshot -> exit 2, not a stack trace
    with open(path, "w") as f:
        json.dump({"version": 1}, f)
    assert main(["slo", "--in", path]) == 2
    assert main(["slo"]) == 2


def test_watch_cli_offline_frame(tmp_path, capsys):
    from paddle_tpu.obs.__main__ import main

    registry = MetricsRegistry()
    registry.counter("serving_requests_submitted_total").inc(3)
    mpath = str(tmp_path / "metrics.json")
    with open(mpath, "w") as f:
        f.write(registry.snapshot_json())
    report = SLOSet().evaluate({}, now=1.0)
    rpath = str(tmp_path / "slo.json")
    with open(rpath, "w") as f:
        json.dump(report, f)
    assert main(["watch", "--in", mpath, "--slo-in", rpath]) == 0
    out = capsys.readouterr().out
    assert "serving health" in out and "[OK] ok" in out
    assert out.count("submitted") == 1  # exactly one frame
    assert main(["watch"]) == 2


def test_exporter_handler_error_returns_500_json():
    """ISSUE 13 satellite: a broken render must not kill the server
    thread OR pass silently — the scrape gets an HTTP 500 with a JSON
    error body, and ``exporter_errors_total`` counts it (so the
    failure shows up in the very next successful scrape)."""
    registry = MetricsRegistry()

    class _Boom:
        def evaluate(self, *a, **k):
            raise ValueError("kaboom")

    exporter = MetricsExporter(registry, slos=_Boom()).start()
    try:
        status, body = _get(exporter.url("/slo"))
        assert status == 500
        err = json.loads(body)
        assert err == {"error": "ValueError: kaboom"}
        status, body = _get(exporter.url("/healthz"))
        assert status == 500
        assert registry.get("exporter_errors_total").value() == 2.0
        # the endpoint survived: a healthy route still serves, and the
        # error counter rides the scrape
        status, prom = _get(exporter.url("/metrics"))
        assert status == 200 and "exporter_errors_total 2" in prom
    finally:
        exporter.stop()
