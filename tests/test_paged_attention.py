"""Paged/blocked KV-cache decode (reference: the 2.6-era serving op
block_multihead_attention + block pool — unverified, SURVEY.md §0/§2.5):
parity vs the contiguous-cache decode kernel, pool allocator semantics,
and the memory-scales-with-live-tokens claim."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.decode_attention import decode_attention
from paddle_tpu.ops.pallas.paged_attention import (
    paged_decode_attention, paged_cache_write,
)
from paddle_tpu.nlp import PagedKVCachePool


def _ragged_setup(rng, lens, h=8, hk=4, d=64, bs=32):
    """Build a contiguous cache and an equivalent paged pool."""
    b = len(lens)
    s_max = max(lens)
    kc = rng.randn(b, s_max, hk, d).astype("f4")
    vc = rng.randn(b, s_max, hk, d).astype("f4")
    for i, ln in enumerate(lens):  # zero the invalid tail for clarity
        kc[i, ln:] = 0
        vc[i, ln:] = 0
    pool = PagedKVCachePool(num_blocks=64, block_size=bs, num_kv_heads=hk,
                            head_dim=d, dtype=jnp.float32)
    kp = np.zeros((64, bs, hk, d), "f4")
    vp = np.zeros((64, bs, hk, d), "f4")
    for i, ln in enumerate(lens):
        table = pool.ensure(i, ln)
        for pos in range(ln):
            kp[table[pos // bs], pos % bs] = kc[i, pos]
            vp[table[pos // bs], pos % bs] = vc[i, pos]
    tables = pool.block_table_array(range(b))
    seq_lens = pool.seq_lens_array(range(b))
    return kc, vc, jnp.asarray(kp), jnp.asarray(vp), tables, seq_lens


def test_paged_matches_contiguous_decode():
    rng = np.random.RandomState(0)
    lens = [7, 32, 57, 128]
    h, hk, d = 8, 4, 64
    kc, vc, kp, vp, tables, seq_lens = _ragged_setup(rng, lens, h, hk, d)
    q = jnp.asarray(rng.randn(len(lens), h, d), jnp.float32)
    ref = decode_attention(q, jnp.asarray(kc), jnp.asarray(vc),
                           jnp.asarray(lens, jnp.int32))
    out = paged_decode_attention(q, kp, vp, tables, seq_lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_cache_write_then_attend():
    rng = np.random.RandomState(1)
    lens = [15, 40]
    h, hk, d, bs = 4, 2, 64, 32
    kc, vc, kp, vp, tables, seq_lens = _ragged_setup(
        rng, lens, h, hk, d, bs)
    pool = PagedKVCachePool(num_blocks=8, block_size=bs, num_kv_heads=hk,
                            head_dim=d, dtype=jnp.float32)
    # decode one more token per sequence
    k_new = jnp.asarray(rng.randn(2, hk, d), jnp.float32)
    v_new = jnp.asarray(rng.randn(2, hk, d), jnp.float32)
    positions = jnp.asarray(lens, jnp.int32)
    kp2, vp2 = paged_cache_write(kp, vp, k_new, v_new, tables, positions)
    q = jnp.asarray(rng.randn(2, h, d), jnp.float32)
    out = paged_decode_attention(q, kp2, vp2, tables,
                                 positions + 1)
    # contiguous reference with the token appended
    kc2 = np.zeros((2, max(lens) + 1, hk, d), "f4")
    vc2 = np.zeros_like(kc2)
    kc2[:, : max(lens)] = kc
    vc2[:, : max(lens)] = vc
    for i, ln in enumerate(lens):
        kc2[i, ln] = np.asarray(k_new[i])
        vc2[i, ln] = np.asarray(v_new[i])
    ref = decode_attention(q, jnp.asarray(kc2), jnp.asarray(vc2),
                           jnp.asarray([l + 1 for l in lens], jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pool_allocator_reuse_and_memory_claim():
    pool = PagedKVCachePool(num_blocks=16, block_size=32, num_kv_heads=2,
                            head_dim=64, num_layers=2)
    pool.ensure("a", 100)   # 4 blocks
    pool.ensure("b", 10)    # 1 block
    assert pool.blocks_in_use == 5
    per_block = 32 * 2 * 64 * 2  # tokens*heads*dim*bf16
    assert pool.bytes_in_use() == 2 * 2 * 5 * per_block
    pool.free("a")
    assert pool.blocks_in_use == 1
    pool.ensure("c", 128)   # reuses a's blocks
    assert pool.blocks_in_use == 5
    # exhaustion raises
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.ensure("d", 16 * 32)


def test_block_multihead_attention_prefill_then_decode():
    """The incubate functional: prefill writes the pool + varlen flash;
    decode steps match a full-context reference."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention
    from paddle_tpu.nn.functional.attention import _xla_varlen_attention

    rng = np.random.RandomState(2)
    h, hk, d, bs = 4, 2, 64, 32
    lens = [9, 21]
    b = len(lens)
    total = sum(lens)
    pool = PagedKVCachePool(num_blocks=16, block_size=bs, num_kv_heads=hk,
                            head_dim=d, dtype=jnp.float32)
    for i, ln in enumerate(lens):
        pool.ensure(i, ln)
    kcache = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))
    vcache = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))

    qkv_np = rng.randn(total, (h + 2 * hk) * d).astype("f4")
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    out = block_multihead_attention(
        paddle.to_tensor(qkv_np), kcache, vcache,
        seq_lens_encoder=paddle.to_tensor(np.asarray(lens, "i4")),
        seq_lens_decoder=paddle.to_tensor(np.zeros(b, "i4")),
        seq_lens_this_time=paddle.to_tensor(np.asarray(lens, "i4")),
        cu_seqlens_q=paddle.to_tensor(cu), cu_seqlens_k=paddle.to_tensor(cu),
        block_tables=paddle.to_tensor(
            np.asarray(pool.block_table_array(range(b)))),
        num_heads=h, kv_num_heads=hk,
    )
    # reference prefill: causal varlen attention over the same packed qkv
    q = qkv_np[:, : h * d].reshape(total, h, d)
    k = qkv_np[:, h * d : (h + hk) * d].reshape(total, hk, d)
    v = qkv_np[:, (h + hk) * d :].reshape(total, hk, d)
    ref = _xla_varlen_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(cu), jnp.asarray(cu), d ** -0.5, True)
    np.testing.assert_allclose(
        np.asarray(out._value).reshape(total, h, d), np.asarray(ref),
        rtol=2e-5, atol=2e-5)

    # decode one token per sequence; reference = full-context attention
    for i in range(b):
        pool.ensure(i, lens[i] + 1)
    qkv_dec = rng.randn(b, (h + 2 * hk) * d).astype("f4")
    out_dec = block_multihead_attention(
        paddle.to_tensor(qkv_dec), kcache, vcache,
        seq_lens_encoder=paddle.to_tensor(np.zeros(b, "i4")),
        seq_lens_decoder=paddle.to_tensor(np.asarray(lens, "i4")),
        seq_lens_this_time=paddle.to_tensor(np.ones(b, "i4")),
        block_tables=paddle.to_tensor(
            np.asarray(pool.block_table_array(range(b)))),
        num_heads=h, kv_num_heads=hk,
    )
    qd = qkv_dec[:, : h * d].reshape(b, h, d)
    kd = qkv_dec[:, h * d : (h + hk) * d].reshape(b, hk, d)
    vd = qkv_dec[:, (h + hk) * d :].reshape(b, hk, d)
    kc_full = np.zeros((b, max(lens) + 1, hk, d), "f4")
    vc_full = np.zeros_like(kc_full)
    for i, ln in enumerate(lens):
        kc_full[i, :ln] = k[cu[i]:cu[i + 1]]
        vc_full[i, :ln] = v[cu[i]:cu[i + 1]]
        kc_full[i, ln] = kd[i]
        vc_full[i, ln] = vd[i]
    ref_dec = decode_attention(
        jnp.asarray(qd), jnp.asarray(kc_full), jnp.asarray(vc_full),
        jnp.asarray([l + 1 for l in lens], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(out_dec._value).reshape(b, h, d), np.asarray(ref_dec),
        rtol=2e-5, atol=2e-5)


def test_block_mha_mixed_prefill_decode_batch():
    """Round-3 review finding: mixed batches must route per row — the
    decode row attends over its cached context, the prefill row over its
    own new tokens."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention
    from paddle_tpu.nn.functional.attention import _xla_varlen_attention

    rng = np.random.RandomState(3)
    h, hk, d, bs = 4, 2, 64, 32
    pool = PagedKVCachePool(num_blocks=16, block_size=bs, num_kv_heads=hk,
                            head_dim=d, dtype=jnp.float32)
    # row 1 already holds 16 cached tokens
    cached = rng.randn(16, hk, d).astype("f4") * 0.5
    cached_v = rng.randn(16, hk, d).astype("f4") * 0.5
    pool.ensure(1, 16)
    kcache_np = np.zeros((16, bs, hk, d), "f4")
    vcache_np = np.zeros_like(kcache_np)
    t1 = pool._tables[1]
    for pos in range(16):
        kcache_np[t1[pos // bs], pos % bs] = cached[pos]
        vcache_np[t1[pos // bs], pos % bs] = cached_v[pos]
    pool.ensure(0, 8)    # row 0: fresh prefill of 8 tokens
    pool.ensure(1, 17)   # row 1: decode 1 token
    kcache = paddle.to_tensor(kcache_np)
    vcache = paddle.to_tensor(vcache_np)

    qkv_np = rng.randn(9, (h + 2 * hk) * d).astype("f4")  # 8 + 1 tokens
    out = block_multihead_attention(
        paddle.to_tensor(qkv_np), kcache, vcache,
        seq_lens_encoder=paddle.to_tensor(np.asarray([8, 0], "i4")),
        seq_lens_decoder=paddle.to_tensor(np.asarray([0, 16], "i4")),
        seq_lens_this_time=paddle.to_tensor(np.asarray([8, 1], "i4")),
        block_tables=paddle.to_tensor(
            np.asarray(pool.block_table_array(range(2)))),
        num_heads=h, kv_num_heads=hk,
    ).numpy().reshape(9, h, d)

    q = qkv_np[:, : h * d].reshape(9, h, d)
    k = qkv_np[:, h * d : (h + hk) * d].reshape(9, hk, d)
    v = qkv_np[:, (h + hk) * d :].reshape(9, hk, d)
    # row 0 reference: causal self-attention over its 8 tokens
    ref0 = _xla_varlen_attention(
        jnp.asarray(q[:8]), jnp.asarray(k[:8]), jnp.asarray(v[:8]),
        jnp.asarray([0, 8], jnp.int32), jnp.asarray([0, 8], jnp.int32),
        d ** -0.5, True)
    np.testing.assert_allclose(out[:8], np.asarray(ref0), rtol=2e-5, atol=2e-5)
    # row 1 reference: decode over cached 16 + the new token
    kc_full = np.concatenate([cached, k[8:9]], 0)[None]
    vc_full = np.concatenate([cached_v, v[8:9]], 0)[None]
    ref1 = decode_attention(jnp.asarray(q[8:9]), jnp.asarray(kc_full),
                            jnp.asarray(vc_full),
                            jnp.asarray([17], jnp.int32))
    np.testing.assert_allclose(out[8:9], np.asarray(ref1), rtol=2e-5,
                               atol=2e-5)


def test_block_mha_chunked_prefill_attends_cache():
    """A prefill row with dec_lens>0 (chunked prefill) must attend over
    the cached context too, bottom-right aligned."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention
    from paddle_tpu.nn.functional.attention import _xla_varlen_attention

    rng = np.random.RandomState(4)
    h, hk, d, bs = 4, 2, 64, 32
    pool = PagedKVCachePool(num_blocks=8, block_size=bs, num_kv_heads=hk,
                            head_dim=d, dtype=jnp.float32)
    cached_k = rng.randn(10, hk, d).astype("f4") * 0.5
    cached_v = rng.randn(10, hk, d).astype("f4") * 0.5
    pool.ensure(0, 10)
    kcache_np = np.zeros((8, bs, hk, d), "f4")
    vcache_np = np.zeros_like(kcache_np)
    t0 = pool._tables[0]
    for pos in range(10):
        kcache_np[t0[pos // bs], pos % bs] = cached_k[pos]
        vcache_np[t0[pos // bs], pos % bs] = cached_v[pos]
    pool.ensure(0, 16)  # 6 more tokens arriving now
    kcache, vcache = paddle.to_tensor(kcache_np), paddle.to_tensor(vcache_np)

    qkv_np = rng.randn(6, (h + 2 * hk) * d).astype("f4")
    out = block_multihead_attention(
        paddle.to_tensor(qkv_np), kcache, vcache,
        seq_lens_encoder=paddle.to_tensor(np.asarray([6], "i4")),
        seq_lens_decoder=paddle.to_tensor(np.asarray([10], "i4")),
        seq_lens_this_time=paddle.to_tensor(np.asarray([6], "i4")),
        block_tables=paddle.to_tensor(
            np.asarray(pool.block_table_array([0]))),
        num_heads=h, kv_num_heads=hk,
    ).numpy().reshape(6, h, d)

    q = qkv_np[:, : h * d].reshape(6, h, d)
    k = qkv_np[:, h * d : (h + hk) * d].reshape(6, hk, d)
    v = qkv_np[:, (h + hk) * d :].reshape(6, hk, d)
    k_full = np.concatenate([cached_k, k], 0)
    v_full = np.concatenate([cached_v, v], 0)
    ref = _xla_varlen_attention(
        jnp.asarray(q), jnp.asarray(k_full), jnp.asarray(v_full),
        jnp.asarray([0, 6], jnp.int32), jnp.asarray([0, 16], jnp.int32),
        d ** -0.5, True)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_block_mha_inactive_rows_skipped():
    """this_time==0 slots (finished sequences) must contribute nothing
    and not corrupt other rows (round-3 review finding)."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention

    rng = np.random.RandomState(5)
    h, hk, d, bs = 4, 2, 64, 32
    pool = PagedKVCachePool(num_blocks=8, block_size=bs, num_kv_heads=hk,
                            head_dim=d, dtype=jnp.float32)
    cached_k = rng.randn(12, hk, d).astype("f4")
    cached_v = rng.randn(12, hk, d).astype("f4")
    kcache_np = np.zeros((8, bs, hk, d), "f4")
    vcache_np = np.zeros_like(kcache_np)
    pool.ensure(1, 12)
    t1 = pool._tables[1]
    for pos in range(12):
        kcache_np[t1[pos // bs], pos % bs] = cached_k[pos]
        vcache_np[t1[pos // bs], pos % bs] = cached_v[pos]
    pool.ensure(1, 13)
    kcache, vcache = paddle.to_tensor(kcache_np), paddle.to_tensor(vcache_np)

    # row0 finished (this_time 0), row1 decoding — one token total
    qkv_np = rng.randn(1, (h + 2 * hk) * d).astype("f4")
    out = block_multihead_attention(
        paddle.to_tensor(qkv_np), kcache, vcache,
        seq_lens_encoder=paddle.to_tensor(np.asarray([0, 0], "i4")),
        seq_lens_decoder=paddle.to_tensor(np.asarray([0, 12], "i4")),
        seq_lens_this_time=paddle.to_tensor(np.asarray([0, 1], "i4")),
        block_tables=paddle.to_tensor(
            np.asarray(pool.block_table_array(range(2)))),
        num_heads=h, kv_num_heads=hk,
    ).numpy().reshape(1, h, d)

    q = qkv_np[:, : h * d].reshape(1, h, d)
    k = qkv_np[:, h * d : (h + hk) * d].reshape(1, hk, d)
    v = qkv_np[:, (h + hk) * d :].reshape(1, hk, d)
    kc_full = np.concatenate([cached_k, k], 0)[None]
    vc_full = np.concatenate([cached_v, v], 0)[None]
    ref = decode_attention(jnp.asarray(q), jnp.asarray(kc_full),
                           jnp.asarray(vc_full),
                           jnp.asarray([13], jnp.int32))
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_block_mha_quant_arg_validation():
    """Round-5: the quant fusion args are accepted, but inconsistent
    combinations must refuse loudly — int8 pools without scales, scales
    with float pools, or only one of the k/v scale pair."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention

    def call(kc_dtype="f4", **kw):
        return block_multihead_attention(
            paddle.to_tensor(np.zeros((1, 8 * 64), "f4")),
            paddle.to_tensor(np.zeros((2, 32, 2, 64), kc_dtype)),
            paddle.to_tensor(np.zeros((2, 32, 2, 64), kc_dtype)),
            seq_lens_encoder=paddle.to_tensor(np.zeros(1, "i4")),
            seq_lens_decoder=paddle.to_tensor(np.zeros(1, "i4")),
            seq_lens_this_time=paddle.to_tensor(np.ones(1, "i4")),
            block_tables=paddle.to_tensor(np.zeros((1, 1), "i4")),
            num_heads=4, kv_num_heads=2, **kw)

    ones2 = paddle.to_tensor(np.ones(2, "f4"))
    with pytest.raises(ValueError, match="BOTH"):
        call(cache_k_quant_scales=ones2)
    with pytest.raises(ValueError, match="int8"):
        call(kc_dtype="i1")  # int8 pools, no scales
    with pytest.raises(ValueError, match="not int8"):
        call(cache_k_quant_scales=ones2, cache_v_quant_scales=ones2)


def _quant_setup(rng, lens, h=4, hk=2, d=64, bs=32):
    """qkv whose k/v lanes sit exactly on the int8 grid for scale 2.0 —
    quantization is lossless, so int8-cache output must EQUAL float."""
    b, total = len(lens), sum(lens)
    qkv = rng.randn(total, (h + 2 * hk) * d).astype("f4")
    # k/v sections: multiples of 0.5 in [-60, 60] → exact at qs=2.0
    kv = rng.randint(-120, 121, (total, 2 * hk * d)).astype("f4") / 2.0
    qkv[:, h * d:] = kv
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    return qkv, cu


def test_block_mha_int8_kv_cache_matches_float():
    """Prefill + decode with int8 pools and per-head quant scales must
    match the float-pool path exactly when values sit on the quant grid
    (proves the wiring: quantize-on-write, dequant-in-kernel/gather)."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention

    rng = np.random.RandomState(6)
    h, hk, d, bs = 4, 2, 64, 32
    lens = [9, 21]
    b = len(lens)
    qkv_np, cu = _quant_setup(rng, lens, h, hk, d, bs)
    qs = paddle.to_tensor(np.full(hk, 2.0, "f4"))

    def run(int8):
        pool = PagedKVCachePool(num_blocks=16, block_size=bs,
                                num_kv_heads=hk, head_dim=d,
                                dtype=jnp.int8 if int8 else jnp.float32)
        for i, ln in enumerate(lens):
            pool.ensure(i, ln)
        kc = paddle.to_tensor(np.zeros((16, bs, hk, d),
                                       "i1" if int8 else "f4"))
        vc = paddle.to_tensor(np.zeros((16, bs, hk, d),
                                       "i1" if int8 else "f4"))
        quant = dict(cache_k_quant_scales=qs, cache_v_quant_scales=qs) \
            if int8 else {}
        out = block_multihead_attention(
            paddle.to_tensor(qkv_np), kc, vc,
            seq_lens_encoder=paddle.to_tensor(np.asarray(lens, "i4")),
            seq_lens_decoder=paddle.to_tensor(np.zeros(b, "i4")),
            seq_lens_this_time=paddle.to_tensor(np.asarray(lens, "i4")),
            block_tables=paddle.to_tensor(
                np.asarray(pool.block_table_array(range(b)))),
            num_heads=h, kv_num_heads=hk, **quant)
        # decode one token per sequence from the (int8) cache
        for i in range(b):
            pool.ensure(i, lens[i] + 1)
        qkv_dec, _ = _quant_setup(rng2, [1] * b, h, hk, d, bs)
        out_dec = block_multihead_attention(
            paddle.to_tensor(qkv_dec), kc, vc,
            seq_lens_encoder=paddle.to_tensor(np.zeros(b, "i4")),
            seq_lens_decoder=paddle.to_tensor(np.asarray(lens, "i4")),
            seq_lens_this_time=paddle.to_tensor(np.ones(b, "i4")),
            block_tables=paddle.to_tensor(
                np.asarray(pool.block_table_array(range(b)))),
            num_heads=h, kv_num_heads=hk, **quant)
        return (out.numpy(), out_dec.numpy(),
                np.asarray(kc._value), np.asarray(vc._value))

    rng2 = np.random.RandomState(7)
    o_i8, od_i8, kc_i8, _ = run(True)
    rng2 = np.random.RandomState(7)
    o_f, od_f, kc_f, _ = run(False)
    assert kc_i8.dtype == np.int8  # the pool genuinely holds int8
    np.testing.assert_allclose(o_i8, o_f, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(od_i8, od_f, rtol=2e-5, atol=2e-5)
    # the int8 cache dequantizes to exactly the float cache
    np.testing.assert_allclose(kc_i8.astype("f4") / 2.0, kc_f,
                               rtol=0, atol=0)


def test_block_mha_qkv_out_scale_dequant():
    """qkv_out_scale applied inside == pre-scaling the qkv outside."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention

    rng = np.random.RandomState(8)
    h, hk, d, bs = 4, 2, 64, 32
    lens = [7, 12]
    b, total = len(lens), sum(lens)
    nchan = (h + 2 * hk) * d
    qkv_int = rng.randint(-1000, 1000, (total, nchan)).astype("f4")
    scale = (0.001 * (1 + np.arange(nchan) % 5)).astype("f4")

    def run(fused):
        pool = PagedKVCachePool(num_blocks=16, block_size=bs,
                                num_kv_heads=hk, head_dim=d,
                                dtype=jnp.float32)
        for i, ln in enumerate(lens):
            pool.ensure(i, ln)
        kc = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))
        vc = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))
        qkv_in = qkv_int if fused else qkv_int * scale[None, :]
        kw = dict(qkv_out_scale=paddle.to_tensor(scale)) if fused else {}
        out = block_multihead_attention(
            paddle.to_tensor(qkv_in), kc, vc,
            seq_lens_encoder=paddle.to_tensor(np.asarray(lens, "i4")),
            seq_lens_decoder=paddle.to_tensor(np.zeros(b, "i4")),
            seq_lens_this_time=paddle.to_tensor(np.asarray(lens, "i4")),
            block_tables=paddle.to_tensor(
                np.asarray(pool.block_table_array(range(b)))),
            num_heads=h, kv_num_heads=hk, **kw)
        return out.numpy()

    np.testing.assert_allclose(run(True), run(False), rtol=2e-5, atol=2e-5)


def test_block_mha_out_quant_epilogue():
    """out_shift + out_smooth + out_scale: int8 output must equal the
    quantize-outside-the-op reference applied to the float output."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention

    rng = np.random.RandomState(9)
    h, hk, d, bs = 4, 2, 64, 32
    lens = [9, 14]
    b, total = len(lens), sum(lens)
    qkv_np = rng.randn(total, (h + 2 * hk) * d).astype("f4")
    shift = (rng.randn(h * d) * 0.1).astype("f4")
    smooth = (1.0 + rng.rand(h * d)).astype("f4")
    out_scale = 0.02

    def run(**kw):
        pool = PagedKVCachePool(num_blocks=16, block_size=bs,
                                num_kv_heads=hk, head_dim=d,
                                dtype=jnp.float32)
        for i, ln in enumerate(lens):
            pool.ensure(i, ln)
        kc = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))
        vc = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))
        return block_multihead_attention(
            paddle.to_tensor(qkv_np), kc, vc,
            seq_lens_encoder=paddle.to_tensor(np.asarray(lens, "i4")),
            seq_lens_decoder=paddle.to_tensor(np.zeros(b, "i4")),
            seq_lens_this_time=paddle.to_tensor(np.asarray(lens, "i4")),
            block_tables=paddle.to_tensor(
                np.asarray(pool.block_table_array(range(b)))),
            num_heads=h, kv_num_heads=hk, **kw).numpy()

    plain = run()
    fused = run(out_shift=paddle.to_tensor(shift),
                out_smooth=paddle.to_tensor(smooth), out_scale=out_scale)
    assert fused.dtype == np.int8
    expect = np.clip(
        np.round((plain + shift[None]) * smooth[None] / out_scale),
        -128, 127).astype(np.int8)
    # rounding at the .5 boundary may differ by 1 lsb between XLA and
    # numpy round-half-to-even on float noise; require exact match on
    # 99.9% and |diff| <= 1 everywhere
    diff = np.abs(fused.astype(np.int32) - expect.astype(np.int32))
    assert diff.max() <= 1
    assert (diff == 0).mean() > 0.999


def test_masked_mha_out_scale_quant():
    from paddle_tpu.incubate.nn.functional import masked_multihead_attention

    rng = np.random.RandomState(10)
    b, h, hk, d, smax = 2, 4, 2, 64, 32
    lens = np.asarray([9, 17], "i4")
    cache = rng.randn(2, b, smax, hk, d).astype("f4")
    x = rng.randn(b, h, d).astype("f4")
    plain = masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        sequence_lengths=paddle.to_tensor(lens)).numpy()
    scale = 0.015
    q8 = masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        sequence_lengths=paddle.to_tensor(lens), out_scale=scale).numpy()
    assert q8.dtype == np.int8
    expect = np.clip(np.round(plain / scale), -128, 127).astype(np.int8)
    diff = np.abs(q8.astype(np.int32) - expect.astype(np.int32))
    assert diff.max() <= 1
    assert (diff == 0).mean() > 0.999


def test_block_multihead_attention_fused_rope_bias_parity():
    """Round-4 verdict #6: rotary_embs + qkv_bias accepted INSIDE the op
    (reference contract) — parity vs apply-bias-then-rope-then-attend.
    Covers prefill (fresh cache) and a decode step whose rope positions
    must be the ABSOLUTE cache positions, both rope styles."""
    from paddle_tpu.incubate.nn.functional import block_multihead_attention
    from paddle_tpu.nn.functional.rope import apply_rotary_emb

    rng = np.random.RandomState(5)
    h, hk, d, bs = 4, 2, 64, 32
    lens = [7, 13]
    b, total = len(lens), sum(lens)
    max_seq = 64
    inv = 1.0 / (10000.0 ** (np.arange(0, d, 2) / d))
    ang = np.outer(np.arange(max_seq), inv)
    rot_np = np.stack([np.cos(ang), np.sin(ang)]).astype("f4")  # (2,S,D/2)
    bias_np = rng.randn((h + 2 * hk) * d).astype("f4") * 0.1

    for neox in (True, False):
        qkv_np = rng.randn(total, (h + 2 * hk) * d).astype("f4")
        cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)

        def pools():
            pool = PagedKVCachePool(num_blocks=16, block_size=bs,
                                    num_kv_heads=hk, head_dim=d,
                                    dtype=jnp.float32)
            for i, ln in enumerate(lens):
                pool.ensure(i, ln)
            kc = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))
            vc = paddle.to_tensor(np.zeros((16, bs, hk, d), "f4"))
            return pool, kc, vc

        common = dict(
            seq_lens_encoder=paddle.to_tensor(np.asarray(lens, "i4")),
            seq_lens_decoder=paddle.to_tensor(np.zeros(b, "i4")),
            seq_lens_this_time=paddle.to_tensor(np.asarray(lens, "i4")),
            num_heads=h, kv_num_heads=hk,
        )
        # fused path
        pool, kc_f, vc_f = pools()
        out_f = block_multihead_attention(
            paddle.to_tensor(qkv_np), kc_f, vc_f,
            block_tables=paddle.to_tensor(
                np.asarray(pool.block_table_array(range(b)))),
            rotary_embs=paddle.to_tensor(rot_np),
            qkv_bias=paddle.to_tensor(bias_np),
            use_neox_rotary_style=neox, **common)

        # reference: bias + per-token rope applied BEFORE the plain op
        biased = qkv_np + bias_np[None, :]
        q = biased[:, : h * d].reshape(total, h, d)
        k = biased[:, h * d: (h + hk) * d].reshape(total, hk, d)
        pos = np.concatenate([np.arange(ln) for ln in lens]).astype("i4")
        q_r = np.asarray(apply_rotary_emb(
            jnp.asarray(q)[None], jnp.asarray(rot_np[0]),
            jnp.asarray(rot_np[1]), neox=neox,
            position_ids=jnp.asarray(pos)[None])[0])
        k_r = np.asarray(apply_rotary_emb(
            jnp.asarray(k)[None], jnp.asarray(rot_np[0]),
            jnp.asarray(rot_np[1]), neox=neox,
            position_ids=jnp.asarray(pos)[None])[0])
        ref_qkv = np.concatenate(
            [q_r.reshape(total, -1), k_r.reshape(total, -1),
             biased[:, (h + hk) * d:]], axis=1).astype("f4")
        pool2, kc_r, vc_r = pools()
        out_r = block_multihead_attention(
            paddle.to_tensor(ref_qkv), kc_r, vc_r,
            block_tables=paddle.to_tensor(
                np.asarray(pool2.block_table_array(range(b)))),
            **common)
        np.testing.assert_allclose(
            np.asarray(out_f._value), np.asarray(out_r._value),
            rtol=2e-5, atol=2e-5)
        # caches must hold the ROTATED keys
        np.testing.assert_allclose(
            np.asarray(kc_f._value), np.asarray(kc_r._value),
            rtol=2e-5, atol=2e-5)

        # one decode step: fused rope must use ABSOLUTE position len_i
        for i in range(b):
            pool.ensure(i, lens[i] + 1)
            pool2.ensure(i, lens[i] + 1)
        qkv_dec = rng.randn(b, (h + 2 * hk) * d).astype("f4")
        dec_common = dict(
            seq_lens_encoder=paddle.to_tensor(np.zeros(b, "i4")),
            seq_lens_decoder=paddle.to_tensor(np.asarray(lens, "i4")),
            seq_lens_this_time=paddle.to_tensor(np.ones(b, "i4")),
            num_heads=h, kv_num_heads=hk,
        )
        out_fd = block_multihead_attention(
            paddle.to_tensor(qkv_dec), kc_f, vc_f,
            block_tables=paddle.to_tensor(
                np.asarray(pool.block_table_array(range(b)))),
            rotary_embs=paddle.to_tensor(rot_np),
            qkv_bias=paddle.to_tensor(bias_np),
            use_neox_rotary_style=neox, **dec_common)
        biased_d = qkv_dec + bias_np[None, :]
        qd = biased_d[:, : h * d].reshape(b, h, d)
        kd = biased_d[:, h * d: (h + hk) * d].reshape(b, hk, d)
        pos_d = np.asarray(lens, "i4")
        qd_r = np.asarray(apply_rotary_emb(
            jnp.asarray(qd)[None], jnp.asarray(rot_np[0]),
            jnp.asarray(rot_np[1]), neox=neox,
            position_ids=jnp.asarray(pos_d)[None])[0])
        kd_r = np.asarray(apply_rotary_emb(
            jnp.asarray(kd)[None], jnp.asarray(rot_np[0]),
            jnp.asarray(rot_np[1]), neox=neox,
            position_ids=jnp.asarray(pos_d)[None])[0])
        ref_qkv_d = np.concatenate(
            [qd_r.reshape(b, -1), kd_r.reshape(b, -1),
             biased_d[:, (h + hk) * d:]], axis=1).astype("f4")
        out_rd = block_multihead_attention(
            paddle.to_tensor(ref_qkv_d), kc_r, vc_r,
            block_tables=paddle.to_tensor(
                np.asarray(pool2.block_table_array(range(b)))),
            **dec_common)
        np.testing.assert_allclose(
            np.asarray(out_fd._value), np.asarray(out_rd._value),
            rtol=2e-5, atol=2e-5)
