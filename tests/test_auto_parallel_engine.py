"""Auto-parallel Engine: fit/evaluate/predict/save/load over a device
mesh (SURVEY.md §2.3 auto-parallel row; reference
auto_parallel/static/engine.py — unverified)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.parallel import mesh as mesh_state
from paddle_tpu.distributed.auto_parallel import Engine


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


class _ToyData(Dataset):
    def __init__(self, n=64, din=8, classes=4, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, din).astype("float32")
        self.y = (np.abs(self.x.sum(1)).astype("int64") % classes)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _mlp(din=8, classes=4):
    paddle.seed(0)
    return nn.Sequential(
        nn.Linear(din, 32), nn.ReLU(), nn.Linear(32, classes)
    )


def _loss():
    ce = nn.CrossEntropyLoss()
    return lambda out, label: ce(out, label)


def test_engine_fit_decreases_loss():
    model = _mlp()
    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())
    eng = Engine(model, _loss(), opt)
    assert eng._mesh is not None  # default dp mesh over all devices
    hist = eng.fit(_ToyData(), batch_size=16, epochs=4, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_engine_evaluate_and_predict():
    model = _mlp()
    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())
    eng = Engine(model, _loss(), opt, metrics=[Accuracy()])
    eng.fit(_ToyData(), batch_size=16, epochs=3, verbose=0)
    res = eng.evaluate(_ToyData(seed=1), batch_size=16, verbose=0)
    assert "loss" in res and "acc" in res
    outs = eng.predict(_ToyData(seed=1), batch_size=16)
    assert len(outs) == 4 and outs[0].shape == [16, 4]


def test_engine_fleet_strategy_mesh():
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
    }
    model = _mlp()
    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())
    eng = Engine(model, _loss(), opt, strategy=strategy)
    assert eng._mesh.shape["dp"] == 4 and eng._mesh.shape["mp"] == 2
    hist = eng.fit(_ToyData(), batch_size=16, epochs=2, verbose=0)
    assert np.isfinite(hist["loss"][-1])


def test_engine_save_load_roundtrip(tmp_path):
    model = _mlp()
    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())
    eng = Engine(model, _loss(), opt)
    eng.fit(_ToyData(), batch_size=16, epochs=1, verbose=0)
    ref = eng.evaluate(_ToyData(seed=1), batch_size=16, verbose=0)["loss"]
    eng.save(str(tmp_path / "ckpt"))

    model2 = _mlp()
    opt2 = paddle.optimizer.Adam(1e-2, parameters=model2.parameters())
    eng2 = Engine(model2, _loss(), opt2)
    eng2.load(str(tmp_path / "ckpt"))
    got = eng2.evaluate(_ToyData(seed=1), batch_size=16, verbose=0)["loss"]
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_engine_shard_tensor_annotated_model():
    """shard_tensor-annotated weights flow through Engine.fit (GSPMD
    plans the collectives — reference planner/partitioner analog)."""
    from paddle_tpu.distributed.auto_parallel import (
        ProcessMesh, shard_tensor, Shard,
    )

    mesh = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["dp", "mp"])
    model = _mlp()
    shard_tensor(model[0].weight, mesh, [Shard(1)])
    shard_tensor(model[2].weight, mesh, [Shard(0)])
    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())
    eng = Engine(model, _loss(), opt, mesh=mesh)
    hist = eng.fit(_ToyData(), batch_size=16, epochs=2, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_engine_fit_with_validation_data():
    """Per-epoch evaluate must read the live (donated) train-step params."""
    model = _mlp()
    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())
    eng = Engine(model, _loss(), opt, metrics=[Accuracy()])
    hist = eng.fit(
        _ToyData(), valid_data=_ToyData(seed=1), batch_size=16, epochs=3,
        verbose=0,
    )
    assert len(hist["loss"]) == 3 and len(hist["val_acc"]) == 3
    assert len(hist["val_loss"]) == 3


def test_engine_predict_keeps_partial_batch():
    model = _mlp()
    eng = Engine(model, _loss(), paddle.optimizer.Adam(
        1e-2, parameters=model.parameters()))
    outs = eng.predict(_ToyData(n=50), batch_size=16)
    total = sum(o.shape[0] for o in outs)
    assert total == 50  # 16+16+16+2 — final partial batch kept


def test_engine_missing_data_raises():
    model = _mlp()
    eng = Engine(model, _loss(), paddle.optimizer.Adam(
        1e-2, parameters=model.parameters()))
    with pytest.raises(ValueError, match="train_data"):
        eng.fit()
    with pytest.raises(ValueError, match="valid_data"):
        eng.evaluate()
    with pytest.raises(ValueError, match="test_data"):
        eng.predict()
