"""Pipeline-parallel jit engine: schedule correctness, interleave, and
no-silent-fallback guarantees."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel import mesh as mesh_state
from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
    PipelineParallel, PipelineParallelWithInterleave,
)


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def _descs():
    return [
        LayerDesc(nn.Linear, 16, 32), LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, 32, 32), LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, 32, 32), LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, 32, 8),
    ]


from paddle_tpu.parallel.mesh import spec_axes as _spec_axes  # noqa: E402


def _serial_reference(x_np, y_np, steps=3):
    mesh_state.set_mesh(None)
    paddle.seed(7)
    layers = [d.build_layer() for d in _descs()]
    net = nn.Sequential(*layers)
    loss_fn = nn.CrossEntropyLoss()
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    losses = []
    for _ in range(steps):
        loss = loss_fn(net(paddle.to_tensor(x_np)), paddle.to_tensor(y_np))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _pp_run(pp_degree, acc_steps, virtual=None, steps=3):
    mesh_state.set_mesh(None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": pp_degree,
        "sharding_degree": 1,
    }
    strategy.pipeline_configs = {"accumulate_steps": acc_steps}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(7)
    pipe = PipelineLayer(
        layers=_descs(), num_stages=pp_degree,
        loss_fn=nn.CrossEntropyLoss(),
        num_virtual_pipeline_stages=virtual)
    cls = PipelineParallelWithInterleave if virtual else PipelineParallel
    model = cls(pipe, fleet.get_hybrid_communicate_group(), strategy)
    opt = paddle.optimizer.SGD(0.1, parameters=pipe.parameters())

    x_np = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y_np = (np.arange(8) % 8).astype(np.int64)
    losses = []
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # fallback = failure
        for _ in range(steps):
            loss = model.train_batch(
                [paddle.to_tensor(x_np), paddle.to_tensor(y_np)], opt)
            losses.append(float(loss))
    assert model._use_jit and getattr(model, "_engine_validated", False), \
        "jit engine was not used"
    return losses, x_np, y_np


def test_pp2_jit_engine_matches_serial():
    losses, x_np, y_np = _pp_run(pp_degree=2, acc_steps=4)
    ref = _serial_reference(x_np, y_np)
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)


def test_pp4_matches_serial():
    losses, x_np, y_np = _pp_run(pp_degree=4, acc_steps=2)
    ref = _serial_reference(x_np, y_np)
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)


def test_interleave_matches_serial():
    """pp=2 x 2 virtual chunks: round-robin placement, same numerics."""
    losses, x_np, y_np = _pp_run(pp_degree=2, acc_steps=4, virtual=2)
    ref = _serial_reference(x_np, y_np)
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)


def test_interleave_chunk_placement():
    mesh_state.set_mesh(None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": 2, "sharding_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    pipe = PipelineLayer(
        layers=_descs(), num_stages=2, loss_fn=nn.CrossEntropyLoss(),
        num_virtual_pipeline_stages=2)
    assert pipe.num_chunks == 4
    assert [pipe.chunk_stage(c) for c in range(4)] == [0, 1, 0, 1]


def test_pp_amp_scaler_path():
    mesh_state.set_mesh(None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": 2, "sharding_degree": 1,
    }
    strategy.pipeline_configs = {"accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(7)
    pipe = PipelineLayer(layers=_descs(), num_stages=2,
                         loss_fn=nn.CrossEntropyLoss())
    model = PipelineParallel(pipe, fleet.get_hybrid_communicate_group(),
                             strategy)
    opt = paddle.optimizer.SGD(0.1, parameters=pipe.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16).astype(np.float32))
    y = paddle.to_tensor(np.arange(4) % 8)
    loss = model.train_batch([x, y], opt, scaler=scaler)
    assert np.isfinite(float(loss))


def _tp_descs():
    from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (
        ColumnParallelLinear, RowParallelLinear,
    )

    return [
        LayerDesc(ColumnParallelLinear, 16, 32, gather_output=False),
        LayerDesc(nn.ReLU),
        LayerDesc(RowParallelLinear, 32, 32, input_is_parallel=True),
        LayerDesc(nn.ReLU),
        LayerDesc(ColumnParallelLinear, 32, 32, gather_output=True),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, 32, 8),
    ]


@pytest.mark.parametrize("virtual,stage", [
    (None, 2), (2, 2), (None, 3), (2, 3),
])
def test_pp_tp_zero_three_axis_matches_serial(virtual, stage):
    """The north-star topology (BASELINE config #3): PP x TP x
    sharding composed on one 8-device mesh — pp2 stages whose
    sub-meshes carry mp=2 and sharding=2; virtual=2 adds INTERLEAVED PP
    (round-robin chunk placement must re-home TP-sharded params per
    chunk); stage=3 is the literal north-star sharding level — the
    params THEMSELVES are dim-0 sharded over the sharding axis, merged
    minor with the TP spec. Oracle: multi-step losses == mesh-less
    serial. Also asserts the composition is REAL: TP params live
    mp-sharded on their stage sub-mesh, optimizer moments are sharded
    over the sharding axis of the param's own mesh, and (stage 3) each
    device holds ≈ 1/4 of every 2-D TP param (mp2 x sharding2)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")

    x_np = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y_np = (np.arange(8) % 8).astype(np.int64)

    # serial oracle: same descs (mp layers degrade mesh-less), AdamW
    mesh_state.set_mesh(None)
    paddle.seed(7)
    net = nn.Sequential(*[d.build_layer() for d in _tp_descs()])
    loss_fn = nn.CrossEntropyLoss()
    opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters(),
                                 weight_decay=0.01)
    ref = []
    for _ in range(3):
        loss = loss_fn(net(paddle.to_tensor(x_np)), paddle.to_tensor(y_np))
        loss.backward()
        opt.step()
        opt.clear_grad()
        ref.append(float(loss))

    mesh_state.set_mesh(None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
    }
    strategy.pipeline_configs = {"accumulate_steps": 2}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": stage}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(7)
    pipe = PipelineLayer(layers=_tp_descs(), num_stages=2,
                         loss_fn=nn.CrossEntropyLoss(),
                         num_virtual_pipeline_stages=virtual)
    model = fleet.distributed_model(pipe)
    # exact type: Interleave subclasses PipelineParallel, so isinstance
    # would pass vacuously for the plain arm
    assert type(model) is (
        PipelineParallelWithInterleave if virtual else PipelineParallel)
    opt = paddle.optimizer.AdamW(1e-3, parameters=pipe.parameters(),
                                 weight_decay=0.01)
    opt = fleet.distributed_optimizer(opt)
    losses = []
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # fallback = failure
        for _ in range(3):
            loss = model.train_batch(
                [paddle.to_tensor(x_np), paddle.to_tensor(y_np)], opt)
            losses.append(float(loss))
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)

    hcg = fleet.get_hybrid_communicate_group()
    stage_meshes = [hcg.get_stage_mesh(s) for s in range(2)]
    # stage-1's first TP weight: mp-sharded, homed on stage-1's devices
    items1 = pipe.get_stage_items(1)
    tp1 = next(it for it in items1 if hasattr(it, "weight")
               and getattr(it.weight, "is_distributed", False))
    sh = tp1.weight._value.sharding
    assert sh.mesh.devices.tolist() == stage_meshes[1].devices.tolist()
    assert "mp" in _spec_axes(sh.spec)
    if stage == 3:
        # stage-3 fact: the PARAM VALUE is ZeRO-sharded — the sharding
        # axis appears in its spec and each device holds a quarter
        # (mp2 x sharding2) of the full weight, on the stage sub-mesh
        assert "sharding" in _spec_axes(sh.spec)
        full = int(np.prod(tp1.weight._value.shape))
        shard_elems = int(np.prod(
            sh.shard_shape(tp1.weight._value.shape)))
        assert shard_elems * 4 == full
        assert getattr(tp1.weight, "is_sharded", False)
    # its moment state is sharded over the sharding axis of the SAME mesh
    st = opt._state_for(tp1.weight)
    msh = st["moment1"].sharding
    assert msh.mesh.devices.tolist() == stage_meshes[1].devices.tolist()
    assert "sharding" in _spec_axes(msh.spec)
    if virtual:
        # the interleave-specific fact: chunk 2 (stage 1's territory
        # under PLAIN pp2) round-robins back to stage 0 — its TP weight
        # must be re-homed onto stage 0's sub-mesh
        items2 = pipe.get_stage_items(2)
        tp2 = next(it for it in items2 if hasattr(it, "weight")
                   and getattr(it.weight, "is_distributed", False))
        assert (tp2.weight._value.sharding.mesh.devices.tolist()
                == stage_meshes[0].devices.tolist())
