"""paddle.vision.ops (nms/box ops/roi_align) + functional autograd
(jacobian/hessian/vjp/jvp)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_box_iou():
    a = _t(np.array([[0, 0, 2, 2], [0, 0, 1, 1]], "f4"))
    b = _t(np.array([[1, 1, 2, 2]], "f4"))
    iou = np.asarray(paddle.vision.ops.box_iou(a, b)._value)
    np.testing.assert_allclose(iou, [[0.25], [0.0]], atol=1e-6)


def test_nms_basic_and_scores():
    boxes = np.array([
        [0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]], "f4")
    scores = np.array([0.9, 0.8, 0.7], "f4")
    keep = paddle.vision.ops.nms(_t(boxes), 0.5, scores=_t(scores))
    np.testing.assert_array_equal(np.asarray(keep._value), [0, 2])
    # flipping scores keeps box 1 instead of 0
    keep2 = paddle.vision.ops.nms(
        _t(boxes), 0.5, scores=_t(scores[::-1].copy()))
    np.testing.assert_array_equal(np.asarray(keep2._value), [2, 1])


def test_nms_categories_do_not_suppress_each_other():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], "f4")
    scores = np.array([0.9, 0.8], "f4")
    cats = np.array([0, 1], "i4")
    keep = paddle.vision.ops.nms(
        _t(boxes), 0.5, scores=_t(scores), category_idxs=_t(cats),
        categories=[0, 1])
    assert len(np.asarray(keep._value)) == 2


def test_roi_align_constant_field():
    # constant feature map → every aligned cell equals the constant
    feat = np.full((1, 3, 8, 8), 5.0, "f4")
    boxes = np.array([[1.0, 1.0, 5.0, 5.0]], "f4")
    out = paddle.vision.ops.roi_align(
        _t(feat), _t(boxes), _t(np.array([1], "i4")), output_size=2)
    assert out.shape == [1, 3, 2, 2]
    np.testing.assert_allclose(np.asarray(out._value), 5.0, rtol=1e-5)


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], "f4")
    targets = np.array([[1, 1, 9, 9], [6, 4, 14, 16]], "f4")
    enc = paddle.vision.ops.box_coder(
        _t(priors), [1.0, 1.0, 1.0, 1.0], _t(targets))
    dec = paddle.vision.ops.box_coder(
        _t(priors), [1.0, 1.0, 1.0, 1.0], enc,
        code_type="decode_center_size")
    np.testing.assert_allclose(
        np.asarray(dec._value), targets, rtol=1e-4, atol=1e-4)


def test_functional_jacobian_hessian():
    x = _t(np.array([1.0, 2.0], "f4"))
    J = paddle.autograd.jacobian(lambda t: t * t, x)
    np.testing.assert_allclose(
        np.asarray(J._value), np.diag([2.0, 4.0]), rtol=1e-6)
    H = paddle.autograd.hessian(lambda t: (t ** 3).sum(), x)
    np.testing.assert_allclose(
        np.asarray(H._value), np.diag([6.0, 12.0]), rtol=1e-6)


def test_functional_vjp_jvp():
    x = _t(np.array([1.0, 2.0], "f4"))
    out, g = paddle.autograd.vjp(
        lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(float(out), 5.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g._value), [2.0, 4.0], rtol=1e-6)
    out, tang = paddle.autograd.jvp(
        lambda t: (t * t).sum(), x, v=_t(np.array([1.0, 0.0], "f4")))
    np.testing.assert_allclose(float(tang), 2.0, rtol=1e-6)


def test_jacobian_create_graph_is_taped():
    x = _t(np.array([1.0, 2.0], "f4"))
    x.stop_gradient = False
    J = paddle.autograd.jacobian(lambda t: t * t, x, create_graph=True)
    assert not J.stop_gradient
    # d/dx tr(J) = d/dx (2x_0 + 2x_1) = [2, 2]
    (g,) = paddle.grad(paddle.trace(J), [x])
    np.testing.assert_allclose(np.asarray(g._value), [2.0, 2.0], rtol=1e-6)
    # default: detached
    J2 = paddle.autograd.jacobian(lambda t: t * t, x)
    assert J2.stop_gradient


def test_vjp_leaf_count_validation():
    x = _t(np.array([1.0], "f4"))
    with pytest.raises(ValueError, match="leaves"):
        paddle.autograd.vjp(
            lambda t: (t * t).sum(), x,
            v=[_t(np.float32(1.0)), _t(np.float32(2.0))],
        )


def test_vjp_multi_input_returns_tuple():
    x = _t(np.array([1.0], "f4"))
    y = _t(np.array([2.0], "f4"))
    out, grads = paddle.autograd.vjp(lambda a, b: (a * b).sum(), [x, y])
    assert isinstance(grads, tuple) and len(grads) == 2
    np.testing.assert_allclose(float(grads[0]), 2.0, rtol=1e-6)


def test_nms_empty_boxes():
    keep = paddle.vision.ops.nms(_t(np.zeros((0, 4), "f4")), 0.5)
    assert keep.shape == [0]


def test_box_coder_scalar_variance():
    priors = np.array([[0, 0, 10, 10]], "f4")
    targets = np.array([[1, 1, 9, 9]], "f4")
    enc_half = paddle.vision.ops.box_coder(_t(priors), 0.5, _t(targets))
    enc_one = paddle.vision.ops.box_coder(
        _t(priors), [1.0, 1.0, 1.0, 1.0], _t(targets))
    np.testing.assert_allclose(
        np.asarray(enc_half._value), 2 * np.asarray(enc_one._value),
        rtol=1e-5)


def test_vjp_outputs_stay_on_tape():
    x = _t(np.array([1.0, 2.0], "f4"))
    x.stop_gradient = False
    out, g = paddle.autograd.vjp(lambda t: (t ** 3).sum(), x)
    (gg,) = paddle.grad(g.sum(), [x])  # d/dx sum(3x^2) = 6x
    np.testing.assert_allclose(np.asarray(gg._value), [6.0, 12.0], rtol=1e-5)


def test_affine_grid_and_grid_sample_identity():
    import paddle_tpu.nn.functional as F

    x = _t(np.random.RandomState(0).randn(2, 3, 5, 7).astype("f4"))
    theta = _t(np.tile(np.array([[1, 0, 0], [0, 1, 0]], "f4"), (2, 1, 1)))
    grid = F.affine_grid(theta, [2, 3, 5, 7])
    out = F.grid_sample(x, grid)
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(x._value), rtol=1e-4, atol=1e-4)


def test_grid_sample_shift_translates():
    import paddle_tpu.nn.functional as F

    x = np.zeros((1, 1, 4, 4), "f4")
    x[0, 0, 1, 1] = 1.0
    # shift grid by one pixel in x: sample at (col+1)
    theta = _t(np.array([[[1, 0, 2.0 / 3], [0, 1, 0]]], "f4"))
    grid = F.affine_grid(theta, [1, 1, 4, 4])
    out = np.asarray(F.grid_sample(_t(x), grid)._value)
    assert out[0, 0, 1, 0] == pytest.approx(1.0, abs=1e-5)


def test_grid_sample_grads_flow():
    import paddle_tpu.nn.functional as F

    x = _t(np.random.RandomState(1).randn(1, 2, 4, 4).astype("f4"))
    x.stop_gradient = False
    theta = _t(np.array([[[1, 0, 0.1], [0, 1, -0.1]]], "f4"))
    grid = F.affine_grid(theta, [1, 2, 4, 4])
    out = F.grid_sample(x, grid)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(np.asarray(x.grad._value)).all()


def test_grid_sample_reflection_identity_in_range():
    import paddle_tpu.nn.functional as F

    x = _t(np.arange(16, dtype="f4").reshape(1, 1, 4, 4))
    theta = _t(np.array([[[1, 0, 0], [0, 1, 0]]], "f4"))
    grid = F.affine_grid(theta, [1, 1, 4, 4])
    out = F.grid_sample(x, grid, padding_mode="reflection")
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(x._value), rtol=1e-5)
