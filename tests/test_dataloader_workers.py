"""Multiprocess DataLoader workers (SURVEY.md §2.4 DataLoader row;
reference python/paddle/io/dataloader/worker.py — unverified)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset


class RangeSquares(Dataset):
    """Module-level (picklable) dataset."""

    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.float32(i) ** 2, np.int64(i)


def _bad_getitem(self, i):
    raise RuntimeError("boom from worker")


class Failing(Dataset):
    def __len__(self):
        return 8

    __getitem__ = _bad_getitem


def _init_fn(worker_id):
    import os

    os.environ["PADDLE_TPU_TEST_WORKER"] = str(worker_id)


def test_process_workers_ordered_and_complete():
    dl = DataLoader(RangeSquares(32), batch_size=4, num_workers=2)
    xs, ys = [], []
    for x, y in dl:
        xs.append(np.asarray(x._value))
        ys.append(np.asarray(y._value))
    xs = np.concatenate(xs)
    ys = np.concatenate(ys)
    np.testing.assert_allclose(ys, np.arange(32))  # strict order
    np.testing.assert_allclose(xs, np.arange(32, dtype="f4") ** 2)


def test_process_workers_propagate_errors():
    dl = DataLoader(Failing(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(dl)


def test_persistent_workers_two_epochs():
    dl = DataLoader(
        RangeSquares(16), batch_size=4, num_workers=2,
        persistent_workers=True,
    )
    for _ in range(2):
        ys = np.concatenate([np.asarray(y._value) for _, y in dl])
        np.testing.assert_allclose(ys, np.arange(16))
    assert dl._executor is not None  # kept alive across epochs
    dl._executor.shutdown(wait=False)
    dl._executor = None


def test_unpicklable_dataset_falls_back_to_thread():
    class Local(Dataset):  # local class: not picklable
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.float32(i)

    dl = DataLoader(Local(), batch_size=4, num_workers=2)
    out = np.concatenate([np.asarray(b._value) for b in dl])
    np.testing.assert_allclose(out, np.arange(8, dtype="f4"))


def test_worker_init_fn_runs():
    dl = DataLoader(
        RangeSquares(8), batch_size=4, num_workers=1,
        worker_init_fn=_init_fn,
    )
    assert len(list(dl)) == 2


def test_close_cleans_claim_dir_and_pool():
    """Regression (round-2 advisor): the worker-id claim dir must not
    leak, and persistent pools must be shut down by close()."""
    import os

    ds = RangeSquares(16)
    dl = DataLoader(ds, batch_size=4, num_workers=2,
                    persistent_workers=True)
    list(dl)
    claim = dl._claim_dir
    assert claim is not None and os.path.isdir(claim)
    assert dl._executor is not None  # persistent: survives the epoch
    dl.close()
    assert dl._executor is None
    assert not os.path.exists(claim)
    # non-persistent: epoch end cleans up automatically
    dl2 = DataLoader(ds, batch_size=4, num_workers=2)
    list(dl2)
    assert dl2._executor is None and dl2._claim_dir is None
