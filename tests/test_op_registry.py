"""The kernel-registry analog (reference: phi::KernelFactory /
PD_REGISTER_KERNEL, SURVEY.md §2.1 — unverified): populated at import
from the public op surface, extended at dispatch time with seam names,
introspectable via paddle.utils, and backing AMP list validation."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle


def test_registry_populated_at_import():
    assert len(paddle.OP_REGISTRY) >= 400, len(paddle.OP_REGISTRY)
    for name in ("matmul", "concat", "exp", "functional.softmax",
                 "functional.relu", "functional.cross_entropy",
                 "linalg.svd", "fft.fft"):
        assert name in paddle.OP_REGISTRY, name
    assert callable(paddle.OP_REGISTRY["matmul"])


def test_dispatch_seam_names_recorded():
    from paddle_tpu.core.dispatch import SEAM_OPS

    x = paddle.to_tensor(np.random.randn(2, 8, 4, 64).astype("f4"))
    import paddle_tpu.nn.functional as F

    F.scaled_dot_product_attention(x, x, x)
    assert ("flash_attention" in SEAM_OPS
            or "scaled_dot_product_attention" in SEAM_OPS)
    assert "flash_attention" in paddle.utils.get_registered_ops() or \
        "scaled_dot_product_attention" in paddle.utils.get_registered_ops()


def test_utils_introspection():
    ops = paddle.utils.get_registered_ops()
    assert ops == sorted(ops) and "matmul" in ops
    assert callable(paddle.utils.get_op_callable("matmul"))
    with pytest.raises(KeyError):
        paddle.utils.get_op_callable("definitely_not_an_op_xyz")


def test_register_op_decorator_seam():
    def my_kernel(v):
        return v + 1

    paddle.register_op("custom_test_op", my_kernel)
    assert paddle.OP_REGISTRY["custom_test_op"] is my_kernel


def test_amp_custom_lists_validated_against_registry():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with paddle.amp.auto_cast(custom_white_list={"matmul"}):
            pass
    assert not [x for x in w if issubclass(x.category, RuntimeWarning)]
    with pytest.warns(RuntimeWarning, match=r"not \(yet\) in the op registry"):
        with paddle.amp.auto_cast(custom_white_list={"not_a_real_op_qq"}):
            pass
