"""OpTest harness — the numpy-oracle + numeric-gradient test pattern.

Replicates the semantics of the reference's crown-jewel test harness
(test/legacy_test/op_test.py — unverified path, SURVEY.md §4): each op
test supplies inputs and a NumPy reference; ``check_output`` compares
forward results, ``check_grad`` compares analytic gradients against
central finite differences. A jit cross-check replaces the reference's
eager-vs-static cross-check.
"""
from __future__ import annotations

import numpy as np
import jax

import paddle_tpu as paddle


def _to_numpy(out):
    if isinstance(out, paddle.Tensor):
        return out.numpy()
    return np.asarray(out)


class OpTest:
    """Base class; subclasses set ``self.op`` and call the checkers."""

    rtol = 1e-5
    atol = 1e-6
    grad_rtol = 1e-2
    grad_atol = 1e-3
    fd_eps = 1e-3

    def check_output(self, op, np_ref, inputs, jit_check=True, **kwargs):
        """op(paddle tensors) vs np_ref(numpy arrays); also under jax.jit."""
        tensors = [paddle.to_tensor(x) for x in inputs]
        out = op(*tensors, **kwargs)
        ref = np_ref(*[np.asarray(x) for x in inputs])
        outs = out if isinstance(out, (tuple, list)) else [out]
        refs = ref if isinstance(ref, (tuple, list)) else [ref]
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(
                _to_numpy(o), r, rtol=self.rtol, atol=self.atol,
                err_msg=f"forward mismatch for {op}",
            )
        if jit_check:
            jitted = jax.jit(lambda *ts: op(*ts, **kwargs))
            jout = jitted(*tensors)
            jouts = jout if isinstance(jout, (tuple, list)) else [jout]
            for o, r in zip(jouts, refs):
                np.testing.assert_allclose(
                    _to_numpy(o), r, rtol=self.rtol, atol=self.atol,
                    err_msg=f"jit forward mismatch for {op}",
                )
        return out

    def check_grad(self, op, inputs, grad_input_idx=None, out_index=None, **kwargs):
        """Analytic grad (tape backward) vs central finite differences."""
        inputs = [np.asarray(x, np.float64) for x in inputs]
        n = len(inputs)
        grad_input_idx = grad_input_idx if grad_input_idx is not None else range(n)

        def scalar_fn(*arrays):
            ts = [paddle.to_tensor(a.astype(np.float32)) for a in arrays]
            out = op(*ts, **kwargs)
            if isinstance(out, (tuple, list)):
                out = out[out_index or 0]
            return float(out.sum().numpy())

        # analytic
        ts = [
            paddle.to_tensor(a.astype(np.float32), stop_gradient=False)
            for a in inputs
        ]
        out = op(*ts, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[out_index or 0]
        out.sum().backward()

        for i in grad_input_idx:
            analytic = ts[i].grad.numpy().astype(np.float64)
            numeric = np.zeros_like(inputs[i])
            flat = inputs[i].reshape(-1)
            num_flat = numeric.reshape(-1)
            for j in range(flat.size):
                orig = flat[j]
                flat[j] = orig + self.fd_eps
                f_plus = scalar_fn(*inputs)
                flat[j] = orig - self.fd_eps
                f_minus = scalar_fn(*inputs)
                flat[j] = orig
                num_flat[j] = (f_plus - f_minus) / (2 * self.fd_eps)
            np.testing.assert_allclose(
                analytic, numeric, rtol=self.grad_rtol, atol=self.grad_atol,
                err_msg=f"gradient mismatch for {op} input {i}",
            )
