"""Flagship model family tests: Llama/GPT forward+train, decode-cache
parity, and the hybrid parallel==serial oracle through the fully-jitted
train step (the bench/dryrun path)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel import mesh as mesh_state
from paddle_tpu.nlp import (
    LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    GPTConfig, GPTForCausalLM,
)
from paddle_tpu.jit.train import JittedTrainStep


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def test_llama_forward_backward_eager():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    m = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 16)))
    logits = m(ids)
    assert logits.shape == [2, 16, 128]
    loss = LlamaPretrainingCriterion()(logits, ids)
    loss.backward()
    g = m.llama.layers[0].self_attn.q_proj.weight.grad
    assert g is not None and float(paddle.abs(g).sum()) > 0


def test_llama_decode_cache_matches_full_forward():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 24)))
    step = paddle.to_tensor(rng.randint(0, 128, (2, 1)))

    caches = m.init_caches(2, 64)
    _, caches = m(ids, position_offset=0, caches=caches)
    lg, caches = m(step, position_offset=24, caches=caches)

    full = m(paddle.concat([ids, step], axis=1))
    np.testing.assert_allclose(
        lg.numpy()[:, 0], full.numpy()[:, -1], atol=2e-5
    )


def test_llama_recompute_matches_plain():
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, 128, (2, 16))

    def loss_with(recompute):
        paddle.seed(0)
        cfg = LlamaConfig.tiny(tensor_parallel=False, use_recompute=recompute)
        m = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(ids_np)
        loss = LlamaPretrainingCriterion()(m(ids), ids)
        loss.backward()
        g = m.llama.layers[0].self_attn.q_proj.weight.grad.numpy()
        return float(loss), g

    l1, g1 = loss_with(False)
    l2, g2 = loss_with(True)
    assert abs(l1 - l2) < 1e-5
    np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-5)


def test_gpt_forward():
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig.tiny())
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 16)))
    assert m(ids).shape == [2, 16, 128]


def _train_losses(parallel, steps=3):
    mesh_state.set_mesh(None)
    if parallel:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
            "sharding_degree": 2,
        }
        fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=True)
    m = LlamaForCausalLM(cfg)
    crit = LlamaPretrainingCriterion()
    opt = paddle.optimizer.AdamW(
        1e-3, parameters=m.parameters(), weight_decay=0.01)
    step = JittedTrainStep(
        m, lambda out, labels: crit(out, labels), opt,
        state_sharding_axis="sharding" if parallel else None)
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 128, (4, 32)))
    return [float(step(ids, ids)) for _ in range(steps)]


def test_llama_jitted_hybrid_train_matches_serial():
    """TP(mp=2) x ZeRO(sharding=2) x DP(2) fully-jitted step == serial."""
    lp = _train_losses(True)
    ls = _train_losses(False)
    np.testing.assert_allclose(lp, ls, rtol=5e-4, atol=5e-5)


def test_jitted_multi_step_scan_matches_single_steps():
    """run_steps (K steps per dispatch via lax.scan) == K single steps."""
    mesh_state.set_mesh(None)

    def build():
        paddle.seed(0)
        cfg = LlamaConfig.tiny(tensor_parallel=False)
        m = LlamaForCausalLM(cfg)
        crit = LlamaPretrainingCriterion()
        opt = paddle.optimizer.AdamW(
            1e-3, parameters=m.parameters(), weight_decay=0.01)
        return JittedTrainStep(m, lambda o, l: crit(o, l), opt)

    rng = np.random.RandomState(2)
    batches = rng.randint(0, 128, (3, 4, 32))

    s1 = build()
    singles = [float(s1(paddle.to_tensor(b), paddle.to_tensor(b)))
               for b in batches]
    s2 = build()
    multi = s2.run_steps(paddle.to_tensor(batches), paddle.to_tensor(batches))
    np.testing.assert_allclose(multi.numpy(), singles, rtol=1e-4, atol=1e-5)


def test_graft_entry_contract():
    """__graft_entry__.entry() compiles single-chip."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (2, 128, 1024)


@pytest.mark.parametrize("gran", ["full", "full_attn", "core_attn",
                                  "selective"])
def test_recompute_granularities_match_plain(gran):
    mesh_state.set_mesh(None)

    def losses(use_recompute):
        paddle.seed(0)
        cfg = LlamaConfig.tiny(
            tensor_parallel=False, use_recompute=use_recompute,
            recompute_granularity=gran,
        )
        m = LlamaForCausalLM(cfg)
        crit = LlamaPretrainingCriterion()
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        step = JittedTrainStep(m, lambda o, l: crit(o, l), opt)
        ids = paddle.to_tensor(
            np.random.RandomState(3).randint(0, 128, (2, 32)))
        return [float(step(ids, ids)) for _ in range(2)]

    np.testing.assert_allclose(losses(True), losses(False),
                               rtol=2e-5, atol=2e-6)


def test_bad_recompute_granularity_raises():
    cfg = LlamaConfig.tiny(tensor_parallel=False, use_recompute=True,
                           recompute_granularity="bogus")
    m = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.zeros((1, 8), "int32"))
    with pytest.raises(ValueError, match="recompute_granularity"):
        m(ids)


def test_core_attn_remat_eager_grads_flow():
    """Regression: attention-only remat must register attention params
    with the tape in eager mode (bare-closure recompute froze them)."""
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False, use_recompute=True,
                           recompute_granularity="core_attn")
    m = LlamaForCausalLM(cfg)
    crit = LlamaPretrainingCriterion()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 16)))
    loss = crit(m(ids), ids)
    loss.backward()
    q = m.llama.layers[0].self_attn.q_proj.weight
    assert q.grad is not None
    assert float(np.abs(np.asarray(q.grad._value)).sum()) > 0


def test_llama_packed_varlen_matches_per_sequence():
    """Packed cu_seqlens training path (round-4): logits of each packed
    segment must equal a separate forward of that segment alone (same
    rope restart, no cross-segment attention), and the packed criterion
    must equal the mean of per-segment shifted CE."""
    mesh_state.set_mesh(None)
    paddle.seed(11)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()

    rng = np.random.RandomState(3)
    lens = [5, 9, 2]
    T = sum(lens)
    ids_np = rng.randint(1, cfg.vocab_size, (1, T)).astype(np.int64)
    cu = np.cumsum([0] + lens).astype(np.int32)

    packed = model(paddle.to_tensor(ids_np),
                   cu_seqlens=paddle.to_tensor(cu))
    packed_np = np.asarray(packed._value)

    for i in range(len(lens)):
        seg = ids_np[:, cu[i]:cu[i + 1]]
        alone = np.asarray(model(paddle.to_tensor(seg))._value)
        np.testing.assert_allclose(
            packed_np[:, cu[i]:cu[i + 1]], alone, rtol=2e-4, atol=2e-4)

    # criterion: boundary positions masked out
    crit = LlamaPretrainingCriterion()
    labels = paddle.to_tensor(ids_np)
    packed_loss = float(crit(packed, labels,
                             cu_seqlens=paddle.to_tensor(cu)))
    tok_losses = []
    for i in range(len(lens)):
        seg = ids_np[:, cu[i]:cu[i + 1]]
        if seg.shape[1] < 2:
            continue
        out = model(paddle.to_tensor(seg))
        import paddle_tpu.nn.functional as F

        per = F.cross_entropy(
            out[:, :-1, :].reshape([-1, cfg.vocab_size]),
            paddle.to_tensor(seg[:, 1:]).reshape([-1]),
            reduction="none")
        tok_losses.extend(np.asarray(per._value).tolist())
    np.testing.assert_allclose(
        packed_loss, float(np.mean(tok_losses)), rtol=2e-4, atol=2e-4)


def test_gpt_recompute_matches_plain():
    """GPT block-level remat (round 4, behind the 40.1% MFU bench
    config): full and selective must reproduce the plain loss AND grads
    (guards the bare-closure param-freezing failure mode)."""
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, 128, (2, 16))

    def loss_with(recompute, gran="full"):
        paddle.seed(0)
        cfg = GPTConfig.tiny(use_recompute=recompute,
                             recompute_granularity=gran)
        m = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(ids_np)
        ce = paddle.nn.CrossEntropyLoss()
        loss = ce(m(ids).reshape([-1, cfg.vocab_size]), ids.reshape([-1]))
        loss.backward()
        return float(loss), m.gpt.blocks[0].qkv.weight.grad.numpy()

    l0, g0 = loss_with(False)
    for gran in ("full", "selective"):
        l1, g1 = loss_with(True, gran)
        assert abs(l0 - l1) < 1e-5, gran
        np.testing.assert_allclose(g0, g1, rtol=1e-4, atol=1e-6,
                                   err_msg=gran)
    with pytest.raises(ValueError, match="recompute_granularity"):
        loss_with(True, "core_attn")


def test_mistral_qwen2_style_configs():
    """Round-5 model-family knobs on the llama stack: Mistral = GQA +
    sliding window (window genuinely cuts attention), Qwen2 =
    attention_bias (q/k/v biases exist, train, and change outputs)."""
    paddle.seed(0)
    cfg_m = LlamaConfig.tiny(tensor_parallel=False, sliding_window=8)
    assert LlamaConfig.mistral_7b().sliding_window == 4096
    assert LlamaConfig.qwen2_7b().attention_bias is True

    m = LlamaForCausalLM(cfg_m)
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(3).randint(0, 128, (1, 24)))
    out = m(ids)
    assert np.isfinite(out.numpy()).all()

    # attention_bias: biases exist on q/k/v (not o), and a train step
    # moves them
    paddle.seed(0)
    cfg_q = LlamaConfig.tiny(tensor_parallel=False, attention_bias=True)
    q = LlamaForCausalLM(cfg_q)
    attn = q.llama.layers[0].self_attn
    assert attn.q_proj.bias is not None
    assert attn.k_proj.bias is not None
    assert attn.v_proj.bias is not None
    assert attn.o_proj.bias is None
    names = [n for n, _ in q.named_parameters()]
    assert any("q_proj.bias" in n for n in names)

    from paddle_tpu.nlp import LlamaPretrainingCriterion

    crit = LlamaPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-2, parameters=q.parameters())
    b0 = attn.q_proj.bias.numpy().copy()
    loss = crit(q(ids), ids)
    loss.backward()
    opt.step()
    assert np.abs(attn.q_proj.bias.numpy() - b0).max() > 0

    # after the update the biases are nonzero → outputs differ from a
    # freshly-built no-bias model with the same seed
    paddle.seed(0)
    nb = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    assert np.abs(q(ids).numpy() - nb(ids).numpy()).max() > 1e-6
