"""BERT/ERNIE family: forward shapes, MLM+NSP pretrain step, finetune,
and the BASELINE config-#4 path (ERNIE pretrain via auto-parallel
Engine on the virtual mesh)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import (
    BertConfig, BertModel, BertForPretraining, BertPretrainingCriterion,
    BertForSequenceClassification, ErnieConfig, ErnieForPretraining,
)
from paddle_tpu.parallel import mesh as mesh_state


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def _ids(b=2, s=16, vocab=128, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randint(0, vocab, (b, s)))


def test_bert_model_shapes():
    paddle.seed(0)
    m = BertModel(BertConfig.tiny())
    hidden, pooled = m(_ids())
    assert hidden.shape == [2, 16, 32] and pooled.shape == [2, 32]


def test_bert_attention_mask_zeroes_padding_influence():
    paddle.seed(0)
    m = BertModel(BertConfig.tiny())
    m.eval()
    ids = _ids()
    mask = np.ones((2, 16), "i4")
    mask[:, 8:] = 0  # padding
    h1, _ = m(ids, attention_mask=paddle.to_tensor(mask))
    ids2 = np.asarray(ids._value).copy()
    ids2[:, 8:] = 7  # change only padded tokens
    h2, _ = m(paddle.to_tensor(ids2), attention_mask=paddle.to_tensor(mask))
    np.testing.assert_allclose(
        np.asarray(h1._value)[:, :8], np.asarray(h2._value)[:, :8],
        rtol=1e-5, atol=1e-6,
    )


def test_bert_pretraining_step_decreases_loss():
    paddle.seed(0)
    cfg = BertConfig.tiny()
    m = BertForPretraining(cfg)
    crit = BertPretrainingCriterion()
    opt = paddle.optimizer.AdamW(5e-3, parameters=m.parameters())
    ids = _ids()
    labels = np.full((2, 16), -100, "i8")
    labels[:, [2, 5, 9]] = np.asarray(ids._value)[:, [2, 5, 9]]
    labels = paddle.to_tensor(labels)
    nsp = paddle.to_tensor(np.array([0, 1], "i8"))
    losses = []
    for _ in range(8):
        scores, rel = m(ids)
        loss = crit(scores, rel, labels, nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_mlm_head_ties_word_embeddings():
    paddle.seed(0)
    m = BertForPretraining(BertConfig.tiny())
    assert m.cls._tied is m.bert.embeddings.word_embeddings.weight


def test_bert_sequence_classification():
    paddle.seed(0)
    m = BertForSequenceClassification(BertConfig.tiny(num_labels=3))
    logits = m(_ids())
    assert logits.shape == [2, 3]


def test_ernie_pretrain_via_auto_parallel_engine():
    """BASELINE config #4: ERNIE pretrain driven by the auto-parallel
    Engine over the device mesh."""
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.distributed import fleet
    from paddle_tpu.io import Dataset

    class MLMData(Dataset):
        def __init__(self, n=32):
            rng = np.random.RandomState(0)
            self.ids = rng.randint(0, 128, (n, 16)).astype("i8")
            self.labels = np.full((n, 16), -100, "i8")
            self.labels[:, [1, 4, 7]] = self.ids[:, [1, 4, 7]]

        def __len__(self):
            return len(self.ids)

        def __getitem__(self, i):
            return self.ids[i], self.labels[i]

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    paddle.seed(0)
    model = ErnieForPretraining(ErnieConfig.tiny())
    crit = BertPretrainingCriterion()

    def loss_fn(outputs, labels):
        scores, rel = outputs
        return crit(scores, rel, labels)

    opt = paddle.optimizer.AdamW(5e-3, parameters=model.parameters())
    eng = Engine(model, loss_fn, opt, strategy=strategy)
    hist = eng.fit(MLMData(), batch_size=16, epochs=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_bert_pad_token_mask_derived():
    """attention_mask=None derives padding from pad_token_id (reference
    behavior)."""
    paddle.seed(0)
    cfg = BertConfig.tiny(pad_token_id=0)
    m = BertModel(cfg)
    m.eval()
    ids = np.asarray(_ids()._value).copy()
    ids[:, 8:] = 0  # pads
    ids[ids == 0] = np.where(
        np.arange(ids.shape[1])[None, :].repeat(2, 0)[ids == 0] < 8, 3, 0)
    h1, _ = m(paddle.to_tensor(ids))
    ids2 = ids.copy()
    # changing nothing (pads already masked): re-run equals
    h2, _ = m(paddle.to_tensor(ids2))
    np.testing.assert_allclose(
        np.asarray(h1._value), np.asarray(h2._value), rtol=1e-6)
    # explicit mask equivalent to the derived one
    mask = (ids != 0).astype("i4")
    h3, _ = m(paddle.to_tensor(ids), attention_mask=paddle.to_tensor(mask))
    np.testing.assert_allclose(
        np.asarray(h1._value), np.asarray(h3._value), rtol=1e-5, atol=1e-6)


def test_bert_additive_and_4d_masks():
    paddle.seed(0)
    m = BertModel(BertConfig.tiny())
    m.eval()
    ids = _ids()
    keep = np.ones((2, 16), "i4")
    keep[:, 12:] = 0
    ref, _ = m(ids, attention_mask=paddle.to_tensor(keep))
    # float additive 2D mask {0, -1e9}
    additive = np.where(keep.astype(bool), 0.0, -1e9).astype("f4")
    h2, _ = m(ids, attention_mask=paddle.to_tensor(additive))
    np.testing.assert_allclose(
        np.asarray(ref._value), np.asarray(h2._value), rtol=1e-5, atol=1e-6)
    # pre-built 4D additive mask
    h3, _ = m(ids, attention_mask=paddle.to_tensor(
        additive[:, None, None, :]))
    np.testing.assert_allclose(
        np.asarray(ref._value), np.asarray(h3._value), rtol=1e-5, atol=1e-6)


def test_untied_lm_head_owns_decoder():
    from paddle_tpu.nlp.bert import BertLMPredictionHead

    paddle.seed(0)
    head = BertLMPredictionHead(BertConfig.tiny())
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4, 32).astype("f4"))
    out = head(x)
    assert out.shape == [2, 4, 128]
