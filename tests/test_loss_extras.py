"""New loss layers (CTC, soft-margin family, Gaussian/Poisson NLL,
PairwiseDistance, Unflatten) vs scipy/torch-formula oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_ctc_loss_matches_simple_case():
    # T=4, B=1, C=3 (blank=0); target [1,2]
    rng = np.random.RandomState(0)
    logits = rng.randn(4, 1, 3).astype("f4")
    log_probs = paddle.nn.functional.log_softmax(_t(logits), axis=-1)
    labels = _t(np.array([[1, 2]], "i4"))
    loss = nn.CTCLoss(blank=0, reduction="sum")(
        log_probs, labels, _t(np.array([4], "i4")), _t(np.array([2], "i4")))
    # brute-force: sum over all valid alignments
    lp = np.asarray(log_probs._value)[:, 0, :]
    import itertools

    total = -np.inf
    for path in itertools.product(range(3), repeat=4):
        # collapse
        out = []
        prev = None
        for s in path:
            if s != prev and s != 0:
                out.append(s)
            prev = s
        if out == [1, 2]:
            ll = sum(lp[t, path[t]] for t in range(4))
            total = np.logaddexp(total, ll)
    np.testing.assert_allclose(float(loss), -total, rtol=1e-4)


def test_ctc_loss_trains():
    paddle.seed(0)
    rng = np.random.RandomState(1)
    logits = paddle.to_tensor(rng.randn(8, 2, 5).astype("f4"))
    logits.stop_gradient = False
    labels = _t(np.array([[1, 2, 3], [2, 2, 0]], "i4"))
    loss = F.ctc_loss(
        paddle.nn.functional.log_softmax(logits, axis=-1), labels,
        _t(np.array([8, 6], "i4")), _t(np.array([3, 2], "i4")))
    loss.backward()
    assert logits.grad is not None
    assert np.isfinite(np.asarray(logits.grad._value)).all()


def test_soft_margin_family():
    x = _t(np.array([[0.5, -1.0]], "f4"))
    y = _t(np.array([[1.0, -1.0]], "f4"))
    loss = F.soft_margin_loss(x, y)
    expect = np.log1p(np.exp(-np.array([0.5, 1.0]))).mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)

    lab = _t(np.array([[1.0, 0.0]], "f4"))
    ml = F.multi_label_soft_margin_loss(x, lab)
    assert np.isfinite(float(ml))

    scores = _t(np.array([[0.1, 0.9, 0.2]], "f4"))
    mm = F.multi_margin_loss(scores, _t(np.array([1], "i8")))
    expect = (max(0, 1 - 0.9 + 0.1) + max(0, 1 - 0.9 + 0.2)) / 3
    np.testing.assert_allclose(float(mm), expect, rtol=1e-5)


def test_nll_losses():
    mu = _t(np.array([1.0, 2.0], "f4"))
    y = _t(np.array([1.5, 1.0], "f4"))
    var = _t(np.array([1.0, 4.0], "f4"))
    g = F.gaussian_nll_loss(mu, y, var)
    expect = 0.5 * (np.log([1.0, 4.0])
                    + np.array([0.25, 1.0]) / np.array([1.0, 4.0]))
    np.testing.assert_allclose(float(g), expect.mean(), rtol=1e-5)

    lx = _t(np.array([0.0, 1.0], "f4"))
    p = F.poisson_nll_loss(lx, _t(np.array([1.0, 2.0], "f4")))
    expect = (np.exp([0.0, 1.0]) - np.array([1.0, 2.0]) * [0.0, 1.0]).mean()
    np.testing.assert_allclose(float(p), expect, rtol=1e-5)


def test_pairwise_distance_and_unflatten():
    a = _t(np.array([[0.0, 0.0], [1.0, 1.0]], "f4"))
    b = _t(np.array([[3.0, 4.0], [1.0, 1.0]], "f4"))
    d = nn.PairwiseDistance()(a, b)
    np.testing.assert_allclose(
        np.asarray(d._value), [5.0, 0.0], rtol=1e-3, atol=2e-3)
    u = nn.Unflatten(1, [2, 3])(_t(np.zeros((4, 6), "f4")))
    assert u.shape == [4, 2, 3]


def test_ctc_mean_divides_by_label_lengths():
    rng = np.random.RandomState(2)
    logits = rng.randn(6, 2, 4).astype("f4")
    lp = paddle.nn.functional.log_softmax(_t(logits), axis=-1)
    labels = _t(np.array([[1, 2, 3], [2, 1, 0]], "i4"))
    in_len = _t(np.array([6, 6], "i4"))
    lab_len = _t(np.array([3, 2], "i4"))
    mean = F.ctc_loss(lp, labels, in_len, lab_len, reduction="mean")
    per = np.asarray(
        F.ctc_loss(lp, labels, in_len, lab_len, reduction="none")._value)
    np.testing.assert_allclose(
        float(mean), (per / np.array([3.0, 2.0])).mean(), rtol=1e-5)


def test_soft_margin_loss_stable_at_extreme_logits():
    loss = F.soft_margin_loss(
        _t(np.array([-100.0], "f4")), _t(np.array([1.0], "f4")))
    np.testing.assert_allclose(float(loss), 100.0, rtol=1e-4)


def test_pairwise_distance_inf_norm():
    d = nn.PairwiseDistance(p=float("inf"))(
        _t(np.array([[0.0, 0.0]], "f4")), _t(np.array([[3.0, 4.0]], "f4")))
    np.testing.assert_allclose(np.asarray(d._value), [4.0], rtol=1e-4)


def test_hsigmoid_loss_custom_path_oracle():
    """Custom path_table/path_code mode vs a numpy BCE-chain oracle,
    plus grads into input and weight."""
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    n, d, nodes = 4, 8, 6
    x_np = rng.randn(n, d).astype("f4")
    w_np = rng.randn(nodes, d).astype("f4")
    b_np = rng.randn(nodes).astype("f4")
    pt = np.asarray([[0, 1, -1], [0, 2, 4], [0, 1, 3], [0, 2, -1]], "i8")
    pc = np.asarray([[1, 0, 0], [0, 1, 1], [1, 1, 0], [0, 0, 0]], "i8")
    lab = np.asarray([0, 1, 2, 3], "i8")

    x = paddle.to_tensor(x_np)
    x.stop_gradient = False
    w = paddle.to_tensor(w_np)
    w.stop_gradient = False
    out = F.hsigmoid_loss(x, paddle.to_tensor(lab), 4, w,
                          bias=paddle.to_tensor(b_np),
                          path_table=paddle.to_tensor(pt),
                          path_code=paddle.to_tensor(pc))
    # numpy oracle
    ref = np.zeros((n, 1), "f4")
    for i in range(n):
        for j in range(pt.shape[1]):
            node = pt[i, j]
            if node < 0:
                continue
            z = float(x_np[i] @ w_np[node] + b_np[node])
            c = float(pc[i, j])
            ref[i, 0] += max(z, 0) - z * c + np.log1p(np.exp(-abs(z)))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)
    out.sum().backward()
    assert float(paddle.abs(x.grad).sum()) > 0
    assert float(paddle.abs(w.grad).sum()) > 0


def test_hsigmoid_loss_default_tree():
    """Default complete-binary-tree mode: every class's path BCE sums;
    sanity — loss falls as the logit chain is trained toward the codes."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.nn.functional.loss import _hsigmoid_default_paths

    num_classes, d = 6, 8
    paths, codes = _hsigmoid_default_paths(num_classes)
    assert paths.shape[0] == num_classes
    # every leaf path stays within the internal-node id range
    assert paths.max() < num_classes - 1 and (paths[paths >= 0] >= 0).all()

    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(5, d).astype("f4"))
    lab = paddle.to_tensor(np.asarray([0, 5, 2, 3, 1], "i8"))
    w = paddle.to_tensor(rng.randn(num_classes - 1, d).astype("f4"))
    out = F.hsigmoid_loss(x, lab, num_classes, w)
    assert out.shape == [5, 1] and np.isfinite(out.numpy()).all()
    # trainable: a few SGD steps on w must reduce the loss
    w.stop_gradient = False
    opt = paddle.optimizer.SGD(0.05, parameters=[w])
    losses = []
    for _ in range(10):
        loss = F.hsigmoid_loss(x, lab, num_classes, w).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
