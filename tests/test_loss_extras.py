"""New loss layers (CTC, soft-margin family, Gaussian/Poisson NLL,
PairwiseDistance, Unflatten) vs scipy/torch-formula oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_ctc_loss_matches_simple_case():
    # T=4, B=1, C=3 (blank=0); target [1,2]
    rng = np.random.RandomState(0)
    logits = rng.randn(4, 1, 3).astype("f4")
    log_probs = paddle.nn.functional.log_softmax(_t(logits), axis=-1)
    labels = _t(np.array([[1, 2]], "i4"))
    loss = nn.CTCLoss(blank=0, reduction="sum")(
        log_probs, labels, _t(np.array([4], "i4")), _t(np.array([2], "i4")))
    # brute-force: sum over all valid alignments
    lp = np.asarray(log_probs._value)[:, 0, :]
    import itertools

    total = -np.inf
    for path in itertools.product(range(3), repeat=4):
        # collapse
        out = []
        prev = None
        for s in path:
            if s != prev and s != 0:
                out.append(s)
            prev = s
        if out == [1, 2]:
            ll = sum(lp[t, path[t]] for t in range(4))
            total = np.logaddexp(total, ll)
    np.testing.assert_allclose(float(loss), -total, rtol=1e-4)


def test_ctc_loss_trains():
    paddle.seed(0)
    rng = np.random.RandomState(1)
    logits = paddle.to_tensor(rng.randn(8, 2, 5).astype("f4"))
    logits.stop_gradient = False
    labels = _t(np.array([[1, 2, 3], [2, 2, 0]], "i4"))
    loss = F.ctc_loss(
        paddle.nn.functional.log_softmax(logits, axis=-1), labels,
        _t(np.array([8, 6], "i4")), _t(np.array([3, 2], "i4")))
    loss.backward()
    assert logits.grad is not None
    assert np.isfinite(np.asarray(logits.grad._value)).all()


def test_soft_margin_family():
    x = _t(np.array([[0.5, -1.0]], "f4"))
    y = _t(np.array([[1.0, -1.0]], "f4"))
    loss = F.soft_margin_loss(x, y)
    expect = np.log1p(np.exp(-np.array([0.5, 1.0]))).mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)

    lab = _t(np.array([[1.0, 0.0]], "f4"))
    ml = F.multi_label_soft_margin_loss(x, lab)
    assert np.isfinite(float(ml))

    scores = _t(np.array([[0.1, 0.9, 0.2]], "f4"))
    mm = F.multi_margin_loss(scores, _t(np.array([1], "i8")))
    expect = (max(0, 1 - 0.9 + 0.1) + max(0, 1 - 0.9 + 0.2)) / 3
    np.testing.assert_allclose(float(mm), expect, rtol=1e-5)


def test_nll_losses():
    mu = _t(np.array([1.0, 2.0], "f4"))
    y = _t(np.array([1.5, 1.0], "f4"))
    var = _t(np.array([1.0, 4.0], "f4"))
    g = F.gaussian_nll_loss(mu, y, var)
    expect = 0.5 * (np.log([1.0, 4.0])
                    + np.array([0.25, 1.0]) / np.array([1.0, 4.0]))
    np.testing.assert_allclose(float(g), expect.mean(), rtol=1e-5)

    lx = _t(np.array([0.0, 1.0], "f4"))
    p = F.poisson_nll_loss(lx, _t(np.array([1.0, 2.0], "f4")))
    expect = (np.exp([0.0, 1.0]) - np.array([1.0, 2.0]) * [0.0, 1.0]).mean()
    np.testing.assert_allclose(float(p), expect, rtol=1e-5)


def test_pairwise_distance_and_unflatten():
    a = _t(np.array([[0.0, 0.0], [1.0, 1.0]], "f4"))
    b = _t(np.array([[3.0, 4.0], [1.0, 1.0]], "f4"))
    d = nn.PairwiseDistance()(a, b)
    np.testing.assert_allclose(
        np.asarray(d._value), [5.0, 0.0], rtol=1e-3, atol=2e-3)
    u = nn.Unflatten(1, [2, 3])(_t(np.zeros((4, 6), "f4")))
    assert u.shape == [4, 2, 3]


def test_ctc_mean_divides_by_label_lengths():
    rng = np.random.RandomState(2)
    logits = rng.randn(6, 2, 4).astype("f4")
    lp = paddle.nn.functional.log_softmax(_t(logits), axis=-1)
    labels = _t(np.array([[1, 2, 3], [2, 1, 0]], "i4"))
    in_len = _t(np.array([6, 6], "i4"))
    lab_len = _t(np.array([3, 2], "i4"))
    mean = F.ctc_loss(lp, labels, in_len, lab_len, reduction="mean")
    per = np.asarray(
        F.ctc_loss(lp, labels, in_len, lab_len, reduction="none")._value)
    np.testing.assert_allclose(
        float(mean), (per / np.array([3.0, 2.0])).mean(), rtol=1e-5)


def test_soft_margin_loss_stable_at_extreme_logits():
    loss = F.soft_margin_loss(
        _t(np.array([-100.0], "f4")), _t(np.array([1.0], "f4")))
    np.testing.assert_allclose(float(loss), 100.0, rtol=1e-4)


def test_pairwise_distance_inf_norm():
    d = nn.PairwiseDistance(p=float("inf"))(
        _t(np.array([[0.0, 0.0]], "f4")), _t(np.array([[3.0, 4.0]], "f4")))
    np.testing.assert_allclose(np.asarray(d._value), [4.0], rtol=1e-4)
