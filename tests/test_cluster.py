"""Cluster tier (ISSUE 15): the multi-engine replica router —
prefix-affinity placement on the pool's own chain keys, consistent-hash
redistribution bounds, health gating (WARN demoted / CRITICAL skipped),
shed coordination (refused only when every replica refused), the
disaggregated prefill->decode hand-off, cluster drain, fleet
snapshot/restore, and the merged ClusterExporter scrape.

Router placement units run against stub engines (pure host logic, no
jax model); everything stream-producing uses the shared tiny llama and
asserts BIT-IDENTICAL outputs vs a single-replica run — the cluster's
core correctness contract."""
import json

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp.paged_cache import (
    PagedKVCachePool, _chain_hash, prompt_prefix_key,
)
from paddle_tpu.obs import ClusterExporter, MetricsExporter, \
    render_dashboard
from paddle_tpu.obs.flight import FlightRecorder, \
    validate_flight_records
from paddle_tpu.serving import (
    BATCH, INTERACTIVE, NORMAL, ClusterFrontDoor, ClusterReplica,
    ClusterRouter, FrontDoorPolicy, ServingEngine, no_shed_policy,
)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


# ------------------------------------------------ prompt_prefix_key
def _pool(num_blocks=16, bs=4):
    return PagedKVCachePool(num_blocks=num_blocks, block_size=bs,
                            num_kv_heads=2, head_dim=8,
                            dtype=jnp.float32, prefix_cache=True)


def test_prompt_prefix_key_matches_pool_chain_exactly():
    """The public key must equal the pool's stored chain hash for the
    same tokens — the router's no-alias-routing guarantee."""
    pool = _pool()
    toks = np.arange(1, 14, dtype=np.int32)  # 3 full blocks + tail
    pool.ensure("a", len(toks))
    pool.publish_prefix("a", toks)
    entries = pool._match_entries(toks)
    assert len(entries) == 3
    # full walk == the deepest published entry's hash
    assert prompt_prefix_key(toks, 4) == entries[-1].hash
    # every capped walk == the entry at that depth
    for d in (1, 2, 3):
        assert prompt_prefix_key(toks, 4, max_blocks=d) \
            == entries[d - 1].hash
    # and the reference chain from the root, by hand
    h = 0
    for i in range(3):
        h = _chain_hash(h, tuple(int(t) for t in toks[4 * i:4 * i + 4]))
    assert prompt_prefix_key(toks, 4) == h


def test_prompt_prefix_key_edges():
    # no full block -> no key (nothing cacheable to be affine to)
    assert prompt_prefix_key([1, 2, 3], 4) is None
    assert prompt_prefix_key([], 4) is None
    # the tail never enters the key
    assert prompt_prefix_key([1, 2, 3, 4, 9], 4) \
        == prompt_prefix_key([1, 2, 3, 4, 7], 4)
    # depth is part of the key: same block at depth 2 differs
    assert prompt_prefix_key([1, 2, 3, 4], 4) \
        != prompt_prefix_key([1, 2, 3, 4] * 2, 4, max_blocks=None)
    with pytest.raises(ValueError):
        prompt_prefix_key([1, 2, 3, 4], 0)


# ------------------------------------------------ router units (stubs)
class _StubPool:
    def __init__(self, block_size):
        self.block_size = block_size
        self.free_blocks = 64
        self.blocks_in_use = 0


class _StubSched:
    def __init__(self):
        self.waiting = []

    def live(self):
        return []


class _StubObs:
    def now(self):
        return 0.0


class _StubCfg:
    num_slots = 4


class _StubEngine:
    """Just enough engine surface for ClusterReplica/ClusterRouter
    placement logic: pool gauges, scheduler depths, a clock, and the
    one-front-door-per-engine token_sink slot."""

    def __init__(self, block_size=4):
        self.pool = _StubPool(block_size)
        self.scheduler = _StubSched()
        self.obs = _StubObs()
        self.config = _StubCfg()
        self.token_sink = None
        self.flight = None
        self.slo = None


def _stub_cluster(n, **kw):
    reps = [ClusterReplica(f"r{i}", _StubEngine()) for i in range(n)]
    return reps, ClusterRouter(reps, **kw)


def _key_toks(rng, n_blocks=2, bs=4):
    return rng.integers(1, 1000, size=n_blocks * bs).tolist()


def test_router_affinity_stable_and_consistent():
    """Same key -> same replica, every time; placement order is
    (affinity head, then failover candidates by load)."""
    reps, router = _stub_cluster(4, vnodes=32)
    rng = np.random.default_rng(0)
    toks = _key_toks(rng)
    first = router.plan(toks)
    assert first[0][1] == "affinity"
    assert all(r == "failover" for _, r in first[1:])
    assert len(first) == 4
    for _ in range(5):
        assert router.plan(toks)[0][0] is first[0][0]
    # sub-block prompt: balance, never affinity
    assert router.plan([1, 2, 3])[0][1] == "balance"


def test_router_redistribution_bound_on_add_remove():
    """Consistent hashing's contract: adding one replica to 4 steals
    only ~1/5 of the keyspace, and every moved key moves TO the new
    replica — old replicas never shuffle keys among themselves.
    Removing it restores the original map exactly."""
    reps, router = _stub_cluster(4, vnodes=64)
    rng = np.random.default_rng(1)
    keys = [_key_toks(rng) for _ in range(300)]
    before = {tuple(k): router.plan(k)[0][0].name for k in keys}
    router.add_replica(ClusterReplica("r4", _StubEngine()))
    after = {tuple(k): router.plan(k)[0][0].name for k in keys}
    moved = [k for k in before if before[k] != after[k]]
    assert all(after[k] == "r4" for k in moved)
    frac = len(moved) / len(keys)
    assert 0.0 < frac < 0.45, f"redistribution {frac:.2f} out of bounds"
    router.remove_replica("r4")
    assert {tuple(k): router.plan(k)[0][0].name for k in keys} == before


def test_router_health_gating():
    """CRITICAL replicas are skipped outright; WARN ones lose even
    their affinity traffic to OK peers; a fully-critical fleet still
    routes (the per-door policy owns that refusal)."""
    reps, router = _stub_cluster(3, vnodes=32)
    rng = np.random.default_rng(2)
    # find a key owned by r1
    toks = None
    for _ in range(200):
        cand = _key_toks(rng)
        if router.plan(cand)[0][0].name == "r1":
            toks = cand
            break
    assert toks is not None
    reps[1].health_state = lambda now: "critical"
    plan = router.plan(toks)
    assert all(rep.name != "r1" for rep, _ in plan)
    assert plan[0][1] == "failover"
    # WARN: demoted below OK peers, even for its own affinity keys
    reps[1].health_state = lambda now: "warn"
    plan = router.plan(toks)
    assert all(rep.name != "r1" for rep, _ in plan)
    # ...but an all-warn fleet still serves, affinity restored
    for r in reps:
        r.health_state = lambda now: "warn"
    assert router.plan(toks)[0][0].name == "r1"
    # all critical: last resort keeps routing
    for r in reps:
        r.health_state = lambda now: "critical"
    assert len(router.plan(toks)) == 3


def test_router_balance_and_round_robin():
    reps, router = _stub_cluster(3, vnodes=32)
    # balance: least-loaded (waiting, live, blocks) wins ties by name
    reps[0].engine.scheduler.waiting = [1, 2]
    reps[1].engine.scheduler.waiting = [1]
    assert router.plan([1, 2, 3])[0][0].name == "r2"
    # round-robin control arm cycles regardless of key
    _, rr = _stub_cluster(3, strategy="round_robin")
    toks = [5, 6, 7, 8]
    order = [rr.plan(toks)[0][0].name for _ in range(6)]
    assert order == ["r0", "r1", "r2", "r0", "r1", "r2"]


def test_router_load_report_serializable():
    reps, router = _stub_cluster(2)
    reports = router.load_reports()
    parsed = json.loads(json.dumps(reports))
    assert parsed[0]["replica"] == "r0"
    assert set(parsed[0]) >= {"state", "waiting", "live", "slots",
                              "free_blocks", "blocks_in_use", "role"}


def test_router_rejects_mismatched_fleets():
    a, b = _StubEngine(block_size=4), _StubEngine(block_size=8)
    with pytest.raises(ValueError, match="block_size"):
        ClusterRouter([ClusterReplica("a", a), ClusterReplica("b", b)])
    with pytest.raises(ValueError, match="duplicate"):
        e1, e2 = _StubEngine(), _StubEngine()
        ClusterRouter([ClusterReplica("x", e1),
                       ClusterReplica("x", e2)])


def test_cluster_drain_interleaves_replicas():
    """Regression (ISSUE 17 satellite): ``ClusterFrontDoor.drain()``
    used to run each replica's door to completion in ring order, so
    replica 0's whole backlog drained before replica N-1 took a single
    step — its accepted requests aged by the sum of every earlier
    replica's backlog. The coordinated drain now pumps the fleet
    interleaved (one overlapped pass per replica per round), so for
    equal backlogs the per-replica step skew stays bounded at 1 at
    EVERY point of the drain, and each door's own ``drain()`` runs on
    an already-idle engine."""
    ledger = []

    class _DrainObs(_StubObs):
        def on_drain(self, *a, **k):
            pass

    class _DrainEngine(_StubEngine):
        def __init__(self, steps):
            super().__init__()
            self.obs = _DrainObs()
            self.steps_left = steps

        @property
        def has_work(self):
            return self.steps_left > 0

    class _DrainDoor:
        """Counting stand-in for ServingFrontDoor's pump halves."""

        def __init__(self, engine, name):
            self.engine = engine
            self._name = name
            self._draining = False

        @property
        def draining(self):
            return self._draining

        def pump_dispatch(self):
            return self._name  # the pending token the collect half eats

        def pump_collect(self, pending):
            assert pending == self._name
            self.engine.steps_left -= 1
            ledger.append(self._name)
            return self.engine.has_work

        def drain(self, flight_path=None):
            assert not self.engine.has_work, \
                "per-door drain must run on an already-idle engine"
            return {"completed": 0, "shed": 0,
                    "preempted": 0, "resumed": 0}

    n_steps = 8
    reps = []
    for name in ("a", "b"):
        eng = _DrainEngine(n_steps)
        reps.append(ClusterReplica(name, eng,
                                   door=_DrainDoor(eng, name)))
    cfd = ClusterFrontDoor(ClusterRouter(reps))
    summary = cfd.drain()
    assert summary["drained"]
    assert len(ledger) == 2 * n_steps
    counts = {"a": 0, "b": 0}
    for name in ledger:
        counts[name] += 1
        assert abs(counts["a"] - counts["b"]) <= 1, (
            f"replica step skew exceeded 1 mid-drain: {ledger}")
    with pytest.raises(ValueError):
        ClusterRouter([])


# ------------------------------------------------ live-cluster e2e
def _mk_replica(model, name, role="general", policy=None, flight=False,
                **eng_kw):
    kw = dict(num_slots=2, block_size=4, prefix_cache=True)
    kw.update(eng_kw)
    if flight:
        kw["flight"] = FlightRecorder()
    eng = ServingEngine(model, **kw)
    return ClusterReplica(name, eng, role=role,
                          policy=policy or no_shed_policy())


def _trace(cfg, n=8, seed=3):
    """Seeded ragged trace with two shared system prefixes — the
    affinity router's bread and butter."""
    rng = np.random.default_rng(seed)
    sys_a = rng.integers(1, cfg.vocab_size, size=8).tolist()
    sys_b = rng.integers(1, cfg.vocab_size, size=8).tolist()
    prompts = []
    for i in range(n):
        tail = rng.integers(1, cfg.vocab_size,
                            size=int(rng.integers(2, 7))).tolist()
        prompts.append((sys_a if i % 2 else sys_b) + tail)
    return prompts


def _run_cluster(model, prompts, n_replicas, max_new_tokens=2, **kw):
    reps = [_mk_replica(model, f"r{i}") for i in range(n_replicas)]
    cfd = ClusterFrontDoor(ClusterRouter(reps, affinity_blocks=2, **kw))
    streams = [cfd.submit(p, max_new_tokens=max_new_tokens, seed=0)
               for p in prompts]
    cfd.run_until_idle()
    return cfd, {s.request.req_id: list(s.result()) for s in streams}


@pytest.fixture(scope="module")
def canon(tiny_model):
    """ONE canonical shared-prefix trace + its cluster-of-1 reference
    streams — the bit-identity oracle every live test below compares
    against (cluster-of-N == cluster-of-1 is the tier's contract, so
    one reference run serves them all and the tier-1 clock)."""
    cfg, model = tiny_model
    prompts = _trace(cfg, n=4)
    _, ref = _run_cluster(model, prompts, 1)
    return prompts, ref


def test_cluster_of_4_bit_identical_to_cluster_of_1(tiny_model, canon):
    """THE contract: callers cannot tell one replica from four — every
    stream byte-identical on the same seeded ragged trace, and the
    shared system prompts actually hit the affinity path."""
    cfg, model = tiny_model
    prompts, ref = canon
    cfd4, out4 = _run_cluster(model, prompts, 4)
    assert out4 == ref
    st = cfd4.router.affinity_stats()
    assert st["keyed_requests"] == len(prompts)
    assert st["affinity_hits"] > 0          # shared prefixes re-landed
    reqs = cfd4.router._c_requests
    assert sum(reqs.value(replica=f"r{i}", reason="affinity")
               for i in range(4)) == len(prompts)


def test_cluster_shed_coordination_failover(tiny_model):
    """A request sheds only when EVERY eligible replica refused it:
    with per-door backpressure at max_waiting=1, the second
    same-prefix submission fails over instead of shedding; a third
    finds the whole fleet full and is refused everywhere."""
    cfg, model = tiny_model
    pol = FrontDoorPolicy(max_waiting=1, preempt=False,
                          backpressure_exempt=INTERACTIVE)
    reps = [_mk_replica(model, f"r{i}", policy=pol) for i in range(2)]
    cfd = ClusterFrontDoor(ClusterRouter(reps, affinity_blocks=1))
    p = list(range(1, 9))
    s1 = cfd.submit(p, max_new_tokens=1, seed=0)       # affinity home
    s2 = cfd.submit(p, max_new_tokens=1, seed=0)       # home full -> fo
    s3 = cfd.submit(p, max_new_tokens=1, seed=0)       # fleet full
    assert not s1.shed and not s2.shed
    assert s3.shed
    reqs = cfd.router._c_requests
    assert sum(reqs.value(replica=f"r{i}", reason="failover")
               for i in range(2)) == 1
    assert cfd.router._c_shed.value(reason="cluster_full") == 1
    cfd.run_until_idle()
    assert list(s1.result()) == list(s2.result())


def test_cluster_victim_selection_on_full_cluster(tiny_model):
    """An INTERACTIVE arrival on a pool-tight replica preempts a BATCH
    victim through the routed door's own ladder — the cluster reuses,
    not reimplements, per-replica preemption. Distinct prompts and no
    prefix cache, so every request carries its full block demand."""
    cfg, model = tiny_model
    pol = FrontDoorPolicy(preempt=True)
    reps = [_mk_replica(model, "r0", policy=pol, num_blocks=10,
                        prefix_cache=False)]
    cfd = ClusterFrontDoor(ClusterRouter(reps))
    rng = np.random.default_rng(5)
    ps = [rng.integers(1, cfg.vocab_size, size=10).tolist()
          for _ in range(3)]
    batch = [cfd.submit(ps[i], max_new_tokens=3, priority=BATCH,
                        seed=0)
             for i in range(2)]
    cfd.pump()                       # both live mid-decode, pool tight
    vip = cfd.submit(ps[2], max_new_tokens=3, priority=INTERACTIVE,
                     seed=0)
    cfd.run_until_idle()
    eng = reps[0].engine
    assert eng.scheduler.preempted_total >= 1
    assert not vip.shed and len(vip.result()) == 3
    for s in batch:
        assert len(s.result()) == 3


def test_cluster_drain_completes_and_exporter_merges(tiny_model, canon):
    """Two fleet-wide contracts on one workload: (a) ``drain()``
    finishes every accepted request and post-drain submissions shed
    with reason ``draining`` on every replica; (b) one
    :class:`ClusterExporter` scrape of the drained fleet == the union
    of per-replica scrapes under a ``replica`` label, fleet
    ``/healthz`` is worst-state-wins, and the watch dashboard renders
    the cluster line off the merged snapshot."""
    cfg, model = tiny_model
    prompts, _ = canon
    reps = [_mk_replica(model, f"r{i}") for i in range(2)]
    cfd = ClusterFrontDoor(ClusterRouter(reps, affinity_blocks=2))
    streams = [cfd.submit(p, max_new_tokens=1, seed=0) for p in prompts]
    summary = cfd.drain()
    assert summary["drained"] and summary["completed"] == len(prompts)
    for s in streams:
        assert len(s.result()) == 1
    # post-drain submissions shed on every replica (reason draining)
    late = cfd.submit(prompts[0], max_new_tokens=1)
    assert late.shed and late.finish_reason == "shed"
    assert cfd.router._c_shed.value(reason="draining") == 1

    exp = ClusterExporter.for_cluster(cfd)
    merged = exp.registry.snapshot()
    # parity: every per-replica series appears relabeled, same value
    for rep in reps:
        for m in rep.engine.obs.registry.snapshot()["metrics"]:
            mm = next(x for x in merged["metrics"]
                      if x["name"] == m["name"])
            for s in m["series"]:
                want = dict(s.get("labels", {}), replica=rep.name)
                hit = [x for x in mm["series"] if x["labels"] == want]
                assert len(hit) == 1, (m["name"], want)
                if "value" in s:
                    assert hit[0]["value"] == s["value"]
    # router series ride unlabeled
    text = exp.registry.prometheus()
    assert "serving_router_requests_total" in text
    assert 'replica="r0"' in text and 'replica="r1"' in text
    # fleet healthz: all vacuously ok -> 200; force one critical -> 503
    status, body = exp.healthz()
    assert status == 200 and body["state"] == "ok"
    exp._members[1] = (exp._members[1][0], _ForcedCritical())
    status, body = exp.healthz()
    assert status == 503 and body["state"] == "critical"
    assert body["replicas"]["r1"] == "critical"
    # live HTTP smoke on the merged endpoints
    import urllib.request
    with ClusterExporter.for_cluster(cfd) as live:
        raw = urllib.request.urlopen(
            live.url("/metrics"), timeout=5).read().decode()
        assert 'replica="r1"' in raw
    # the watch dashboard grows a cluster line off the merged snapshot
    dash = render_dashboard(merged)
    assert " cluster " in dash and "hit" in dash


def test_disaggregated_handoff_bit_identical(tiny_model, canon):
    """Prefill/decode role split: the prefill replica emits t0 and
    publishes the prompt's blocks; the decode replica re-admits via
    recompute-on-resume — the combined stream equals a single-replica
    run, the hand-off is journaled, and the journals stay
    schema-valid."""
    cfg, model = tiny_model
    prompts, canon_ref = canon
    prompts = prompts[:2]
    reps = [_mk_replica(model, "pf", role="prefill", flight=True),
            _mk_replica(model, "dc", role="decode", flight=True)]
    cfd = ClusterFrontDoor(ClusterRouter(reps, affinity_blocks=2))
    streams = [cfd.submit(p, max_new_tokens=2, seed=0)
               for p in prompts]
    cfd.run_until_idle()
    out = {s.request.req_id: list(s.result()) for s in streams}
    ref = {f"c{i}": canon_ref[f"c{i}"] for i in range(len(prompts))}
    assert out == ref
    assert cfd.router._c_handoffs.value() == len(prompts)
    # prefill side published the prompts' blocks into ITS index
    assert reps[0].engine.pool.prefix_cache_stats()["cached_blocks"] > 0
    # flight journals (route + handoff events included) validate
    for rep in reps:
        recs = [json.loads(ln) for ln in
                rep.engine.flight.jsonl().splitlines()]
        if recs:
            validate_flight_records(recs)
        kinds = {e["kind"] for j in rep.engine.flight._live.values()
                 for e in j["events"]}
        if rep.role == "decode":
            assert not kinds & {"submit"}  # all retired by now


def test_fleet_snapshot_restore_roundtrip(tiny_model, canon):
    """Crash mid-decode, restore the whole fleet from the snapshot,
    finish: streams equal the uninterrupted run, and the router's
    affinity map survives (a restored cluster keeps routing warm)."""
    cfg, model = tiny_model
    prompts, ref = canon
    reps = [_mk_replica(model, f"r{i}") for i in range(2)]
    cfd = ClusterFrontDoor(ClusterRouter(reps, affinity_blocks=2))
    for p in prompts:
        cfd.submit(p, max_new_tokens=2, seed=0)
    cfd.pump()                      # partial progress, then "crash"
    snap = json.loads(json.dumps(cfd.snapshot()))  # JSON round-trip
    assert snap["kind"] == "serving_cluster_snapshot"
    restored = ClusterFrontDoor.restore(snap, model,
                                        policy=no_shed_policy())
    streams = restored.streams()
    restored.run_until_idle()
    out = {rid: list(s.result()) for rid, s in streams.items()}
    assert out                       # the crash really caught mid-flight
    done = {rid: toks for rid, toks in ref.items() if rid in out}
    assert out == done
    # everything not mid-flight at the snapshot already completed there
    completed = {str(r.req_id): list(r.tokens)
                 for rep in cfd.replicas
                 for r in rep.engine.completed}
    for rid, toks in ref.items():
        assert (out.get(rid, completed.get(rid))) == toks
    assert restored.router._key_owner == cfd.router._key_owner


class _ForcedCritical:
    def health_report(self, now=None):
        return {"version": 1, "state": "critical", "now": now,
                "objectives": []}
