"""OpTest-style coverage for the math op corpus."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import OpTest

rng = np.random.default_rng(0)


def data(*shape):
    return rng.standard_normal(shape).astype(np.float32)


def pos(*shape):
    return (np.abs(data(*shape)) + 0.5).astype(np.float32)


class TestUnary(OpTest):
    @pytest.mark.parametrize(
        "op,ref,positive",
        [
            (paddle.exp, np.exp, False),
            (paddle.log, np.log, True),
            (paddle.sqrt, np.sqrt, True),
            (paddle.tanh, np.tanh, False),
            (paddle.sin, np.sin, False),
            (paddle.cos, np.cos, False),
            (paddle.abs, np.abs, False),
            (paddle.square, np.square, False),
            (paddle.floor, np.floor, False),
            (paddle.ceil, np.ceil, False),
            (paddle.log1p, np.log1p, True),
            (paddle.expm1, np.expm1, False),
            (paddle.rsqrt, lambda x: 1 / np.sqrt(x), True),
            (paddle.sigmoid, lambda x: 1 / (1 + np.exp(-x)), False),
            (paddle.reciprocal, lambda x: 1 / x, True),
        ],
    )
    def test_forward(self, op, ref, positive):
        x = pos(3, 4) if positive else data(3, 4)
        self.check_output(op, ref, [x])

    @pytest.mark.parametrize(
        "op,positive",
        [
            (paddle.exp, False),
            (paddle.log, True),
            (paddle.sqrt, True),
            (paddle.tanh, False),
            (paddle.sigmoid, False),
        ],
    )
    def test_grad(self, op, positive):
        x = pos(2, 3) if positive else data(2, 3)
        self.check_grad(op, [x])


class TestBinary(OpTest):
    @pytest.mark.parametrize(
        "op,ref",
        [
            (paddle.add, np.add),
            (paddle.subtract, np.subtract),
            (paddle.multiply, np.multiply),
            (paddle.divide, np.divide),
            (paddle.maximum, np.maximum),
            (paddle.minimum, np.minimum),
            (paddle.atan2, np.arctan2),
        ],
    )
    def test_forward(self, op, ref):
        x, y = data(3, 4), pos(3, 4)
        self.check_output(op, ref, [x, y])

    def test_broadcast(self):
        self.check_output(paddle.add, np.add, [data(3, 1, 4), data(2, 1)])

    def test_grad_mul(self):
        self.check_grad(paddle.multiply, [data(2, 3), data(2, 3)])

    def test_grad_div_broadcast(self):
        self.check_grad(paddle.divide, [data(2, 3), pos(3)])

    def test_pow_scalar(self):
        x = pos(3, 4)
        out = paddle.pow(paddle.to_tensor(x), 2.0)
        np.testing.assert_allclose(out.numpy(), x**2, rtol=1e-5)


class TestReduce(OpTest):
    @pytest.mark.parametrize(
        "op,ref",
        [
            (paddle.sum, np.sum),
            (paddle.mean, np.mean),
            (paddle.max, np.max),
            (paddle.min, np.min),
            (paddle.prod, np.prod),
        ],
    )
    @pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False), (1, True), ((0, 2), False)])
    def test_forward(self, op, ref, axis, keepdim):
        if op in (paddle.max, paddle.min) and isinstance(axis, tuple):
            pytest.skip("paddle max/min take int axis")
        x = data(2, 3, 4)
        self.check_output(
            lambda t: op(t, axis=axis, keepdim=keepdim),
            lambda a: ref(a, axis=axis, keepdims=keepdim),
            [x],
        )

    def test_grad_sum(self):
        self.check_grad(lambda t: paddle.sum(t, axis=1), [data(2, 3)])

    def test_grad_mean(self):
        self.check_grad(paddle.mean, [data(2, 3)])

    def test_logsumexp(self):
        from scipy.special import logsumexp

        x = data(3, 4)
        self.check_output(
            lambda t: paddle.logsumexp(t, axis=1),
            lambda a: logsumexp(a, axis=1),
            [x],
        )

    def test_cumsum(self):
        x = data(3, 4)
        self.check_output(
            lambda t: paddle.cumsum(t, axis=1),
            lambda a: np.cumsum(a, axis=1),
            [x],
        )
        self.check_output(
            paddle.cumsum, lambda a: np.cumsum(a.reshape(-1)), [x]
        )


class TestClipScale(OpTest):
    def test_clip(self):
        x = data(3, 4)
        self.check_output(
            lambda t: paddle.clip(t, -0.5, 0.5),
            lambda a: np.clip(a, -0.5, 0.5),
            [x],
        )

    def test_scale(self):
        x = data(3, 4)
        self.check_output(
            lambda t: paddle.scale(t, scale=2.0, bias=1.0),
            lambda a: a * 2 + 1,
            [x],
        )
        self.check_output(
            lambda t: paddle.scale(t, scale=2.0, bias=1.0, bias_after_scale=False),
            lambda a: (a + 1) * 2,
            [x],
        )


class TestDtypes(OpTest):
    def test_int_sum_promotes(self):
        x = np.arange(6, dtype=np.int32).reshape(2, 3)
        out = paddle.sum(paddle.to_tensor(x))
        assert out.numpy() == 15

    def test_bf16_matmul(self):
        x = paddle.ones([4, 4], dtype="bfloat16")
        out = paddle.matmul(x, x)
        assert out.dtype.name == "bfloat16"
        np.testing.assert_allclose(out.astype("float32").numpy(), 4 * np.ones((4, 4)))


class TestFFT:
    """paddle.fft vs numpy oracle, incl. grad through rfft/irfft."""

    def test_fft_roundtrip_and_values(self):
        rng = np.random.RandomState(0)
        x_np = rng.randn(4, 16).astype("float32")
        x = paddle.to_tensor(x_np)
        out = paddle.fft.fft(x)
        np.testing.assert_allclose(
            np.asarray(out._value), np.fft.fft(x_np), rtol=1e-4, atol=1e-4)
        back = paddle.fft.ifft(out)
        np.testing.assert_allclose(
            np.asarray(back._value).real, x_np, rtol=1e-4, atol=1e-5)

    def test_rfft_norms(self):
        rng = np.random.RandomState(1)
        x_np = rng.randn(8, 32).astype("float32")
        x = paddle.to_tensor(x_np)
        for norm in ("backward", "ortho", "forward"):
            out = paddle.fft.rfft(x, norm=norm)
            np.testing.assert_allclose(
                np.asarray(out._value), np.fft.rfft(x_np, norm=norm),
                rtol=1e-4, atol=1e-4)

    def test_fft2_and_fftn(self):
        rng = np.random.RandomState(2)
        x_np = rng.randn(3, 8, 8).astype("float32")
        x = paddle.to_tensor(x_np)
        np.testing.assert_allclose(
            np.asarray(paddle.fft.fft2(x)._value), np.fft.fft2(x_np),
            rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(paddle.fft.fftn(x)._value), np.fft.fftn(x_np),
            rtol=1e-4, atol=1e-3)

    def test_fftshift_fftfreq(self):
        f = paddle.fft.fftfreq(8, d=0.5)
        np.testing.assert_allclose(
            np.asarray(f._value), np.fft.fftfreq(8, d=0.5), rtol=1e-6)
        x = paddle.to_tensor(np.arange(8, dtype="float32"))
        np.testing.assert_allclose(
            np.asarray(paddle.fft.fftshift(x)._value),
            np.fft.fftshift(np.arange(8, dtype="float32")), rtol=1e-6)

    def test_rfft_grad(self):
        rng = np.random.RandomState(3)
        x_np = rng.randn(16).astype("float32")
        x = paddle.to_tensor(x_np)
        x.stop_gradient = False
        y = paddle.fft.irfft(paddle.fft.rfft(x))
        (y * y).sum().backward()
        assert x.grad is not None
        # irfft(rfft(x)) == x, so d/dx sum(x^2) == 2x
        np.testing.assert_allclose(
            np.asarray(x.grad._value), 2 * x_np, rtol=1e-4, atol=1e-4)

    def test_invalid_norm_raises(self):
        x = paddle.to_tensor(np.zeros(4, "float32"))
        with pytest.raises(ValueError):
            paddle.fft.fft(x, norm="bogus")
