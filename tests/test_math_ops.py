"""OpTest-style coverage for the math op corpus."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import OpTest

rng = np.random.default_rng(0)


def data(*shape):
    return rng.standard_normal(shape).astype(np.float32)


def pos(*shape):
    return (np.abs(data(*shape)) + 0.5).astype(np.float32)


class TestUnary(OpTest):
    @pytest.mark.parametrize(
        "op,ref,positive",
        [
            (paddle.exp, np.exp, False),
            (paddle.log, np.log, True),
            (paddle.sqrt, np.sqrt, True),
            (paddle.tanh, np.tanh, False),
            (paddle.sin, np.sin, False),
            (paddle.cos, np.cos, False),
            (paddle.abs, np.abs, False),
            (paddle.square, np.square, False),
            (paddle.floor, np.floor, False),
            (paddle.ceil, np.ceil, False),
            (paddle.log1p, np.log1p, True),
            (paddle.expm1, np.expm1, False),
            (paddle.rsqrt, lambda x: 1 / np.sqrt(x), True),
            (paddle.sigmoid, lambda x: 1 / (1 + np.exp(-x)), False),
            (paddle.reciprocal, lambda x: 1 / x, True),
        ],
    )
    def test_forward(self, op, ref, positive):
        x = pos(3, 4) if positive else data(3, 4)
        self.check_output(op, ref, [x])

    @pytest.mark.parametrize(
        "op,positive",
        [
            (paddle.exp, False),
            (paddle.log, True),
            (paddle.sqrt, True),
            (paddle.tanh, False),
            (paddle.sigmoid, False),
        ],
    )
    def test_grad(self, op, positive):
        x = pos(2, 3) if positive else data(2, 3)
        self.check_grad(op, [x])


class TestBinary(OpTest):
    @pytest.mark.parametrize(
        "op,ref",
        [
            (paddle.add, np.add),
            (paddle.subtract, np.subtract),
            (paddle.multiply, np.multiply),
            (paddle.divide, np.divide),
            (paddle.maximum, np.maximum),
            (paddle.minimum, np.minimum),
            (paddle.atan2, np.arctan2),
        ],
    )
    def test_forward(self, op, ref):
        x, y = data(3, 4), pos(3, 4)
        self.check_output(op, ref, [x, y])

    def test_broadcast(self):
        self.check_output(paddle.add, np.add, [data(3, 1, 4), data(2, 1)])

    def test_grad_mul(self):
        self.check_grad(paddle.multiply, [data(2, 3), data(2, 3)])

    def test_grad_div_broadcast(self):
        self.check_grad(paddle.divide, [data(2, 3), pos(3)])

    def test_pow_scalar(self):
        x = pos(3, 4)
        out = paddle.pow(paddle.to_tensor(x), 2.0)
        np.testing.assert_allclose(out.numpy(), x**2, rtol=1e-5)


class TestReduce(OpTest):
    @pytest.mark.parametrize(
        "op,ref",
        [
            (paddle.sum, np.sum),
            (paddle.mean, np.mean),
            (paddle.max, np.max),
            (paddle.min, np.min),
            (paddle.prod, np.prod),
        ],
    )
    @pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False), (1, True), ((0, 2), False)])
    def test_forward(self, op, ref, axis, keepdim):
        if op in (paddle.max, paddle.min) and isinstance(axis, tuple):
            pytest.skip("paddle max/min take int axis")
        x = data(2, 3, 4)
        self.check_output(
            lambda t: op(t, axis=axis, keepdim=keepdim),
            lambda a: ref(a, axis=axis, keepdims=keepdim),
            [x],
        )

    def test_grad_sum(self):
        self.check_grad(lambda t: paddle.sum(t, axis=1), [data(2, 3)])

    def test_grad_mean(self):
        self.check_grad(paddle.mean, [data(2, 3)])

    def test_logsumexp(self):
        from scipy.special import logsumexp

        x = data(3, 4)
        self.check_output(
            lambda t: paddle.logsumexp(t, axis=1),
            lambda a: logsumexp(a, axis=1),
            [x],
        )

    def test_cumsum(self):
        x = data(3, 4)
        self.check_output(
            lambda t: paddle.cumsum(t, axis=1),
            lambda a: np.cumsum(a, axis=1),
            [x],
        )
        self.check_output(
            paddle.cumsum, lambda a: np.cumsum(a.reshape(-1)), [x]
        )


class TestClipScale(OpTest):
    def test_clip(self):
        x = data(3, 4)
        self.check_output(
            lambda t: paddle.clip(t, -0.5, 0.5),
            lambda a: np.clip(a, -0.5, 0.5),
            [x],
        )

    def test_scale(self):
        x = data(3, 4)
        self.check_output(
            lambda t: paddle.scale(t, scale=2.0, bias=1.0),
            lambda a: a * 2 + 1,
            [x],
        )
        self.check_output(
            lambda t: paddle.scale(t, scale=2.0, bias=1.0, bias_after_scale=False),
            lambda a: (a + 1) * 2,
            [x],
        )


class TestDtypes(OpTest):
    def test_int_sum_promotes(self):
        x = np.arange(6, dtype=np.int32).reshape(2, 3)
        out = paddle.sum(paddle.to_tensor(x))
        assert out.numpy() == 15

    def test_bf16_matmul(self):
        x = paddle.ones([4, 4], dtype="bfloat16")
        out = paddle.matmul(x, x)
        assert out.dtype.name == "bfloat16"
        np.testing.assert_allclose(out.astype("float32").numpy(), 4 * np.ones((4, 4)))
