"""Distributed stack tests on the 8-device CPU mesh.

The key oracle (SURVEY.md §4): parallel == serial numerics — hybrid
sharded/TP/PP runs must match a plain single-logical-device run.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel import mesh as mesh_state


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def _init(dp=1, mp=1, pp=1, sharding=1, acc_steps=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
        "sharding_degree": sharding,
    }
    strategy.pipeline_configs = {"accumulate_steps": acc_steps}
    fleet.init(is_collective=True, strategy=strategy)
    return strategy


def test_topology_ranks():
    topo = fleet.CommunicateTopology(
        ("data", "pipe", "sharding", "sep", "model"), (2, 2, 1, 1, 2)
    )
    assert topo.world_size() == 8
    assert topo.get_rank(data=0, pipe=0, sharding=0, sep=0, model=1) == 1
    coord = topo.get_coord(5)
    assert topo.get_rank(**coord) == 5
    groups = topo.get_comm_list("model")
    assert all(len(g) == 2 for g in groups)


def test_fleet_init_builds_mesh():
    _init(dp=2, mp=2, sharding=2)
    m = mesh_state.get_mesh()
    assert m.shape["dp"] == 2 and m.shape["mp"] == 2 and m.shape["sharding"] == 2
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2


def test_tp_parallel_equals_serial():
    _init(mp=2)
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear,
    )

    paddle.seed(0)
    col = ColumnParallelLinear(8, 16, has_bias=True, gather_output=False)
    row = RowParallelLinear(16, 8, has_bias=True, input_is_parallel=True)
    x = paddle.randn([4, 8])
    out = row(col(x))
    ref = (
        x.numpy() @ col.weight.numpy() + col.bias.numpy()
    ) @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)
    # weights actually sharded over mp
    assert "mp" in str(col.weight._value.sharding.spec)


def test_vocab_parallel_embedding():
    _init(mp=2)
    from paddle_tpu.distributed.fleet.meta_parallel import VocabParallelEmbedding

    emb = VocabParallelEmbedding(16, 8)
    ids = paddle.to_tensor([[1, 3], [5, 15]])
    out = emb(ids)
    np.testing.assert_allclose(
        out.numpy(), emb.weight.numpy()[ids.numpy()], rtol=1e-6
    )


def test_tp_training_matches_serial():
    """Same seed+data: mp-sharded model == unsharded model after k steps."""
    import copy

    def build_and_train(use_mesh):
        mesh_state.set_mesh(None)
        if use_mesh:
            _init(mp=2)
        paddle.seed(42)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear,
        )

        col = ColumnParallelLinear(8, 16, has_bias=True, gather_output=False)
        row = RowParallelLinear(16, 4, has_bias=True, input_is_parallel=True)
        params = col.parameters() + row.parameters()
        opt = paddle.optimizer.SGD(0.1, parameters=params)
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3]))
        for _ in range(3):
            loss = F.cross_entropy(row(col(x)), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        return float(loss), [p.numpy().copy() for p in params]

    loss_p, params_p = build_and_train(True)
    loss_s, params_s = build_and_train(False)
    np.testing.assert_allclose(loss_p, loss_s, rtol=1e-4)
    for a, b in zip(params_p, params_s):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_group_sharded_stage3_equals_serial():
    def run(level):
        mesh_state.set_mesh(None)
        if level:
            _init(sharding=4)
        paddle.seed(7)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = paddle.optimizer.Adam(0.05, parameters=m.parameters())
        if level:
            from paddle_tpu.distributed.sharding import group_sharded_parallel

            m2, opt, _ = group_sharded_parallel(m, opt, level)
        else:
            m2 = m
        x = paddle.to_tensor(np.random.RandomState(1).randn(8, 8).astype(np.float32))
        y = paddle.to_tensor(np.arange(8) % 4)
        for _ in range(3):
            loss = F.cross_entropy(m2(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        return float(loss), [p.numpy().copy() for p in m.parameters()]

    for level in ("os", "os_g", "p_g_os"):
        loss_p, params_p = run(level)
        loss_s, params_s = run(None)
        np.testing.assert_allclose(loss_p, loss_s, rtol=1e-4, err_msg=level)
        for a, b in zip(params_p, params_s):
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5, err_msg=level)


def test_pipeline_parallel_trains():
    _init(dp=2, mp=2, pp=2, acc_steps=4)
    paddle.seed(0)
    descs = [
        fleet.LayerDesc(nn.Linear, 8, 32),
        fleet.LayerDesc(nn.ReLU),
        fleet.LayerDesc(nn.Linear, 32, 32),
        fleet.LayerDesc(nn.ReLU),
        fleet.LayerDesc(nn.Linear, 32, 4),
    ]
    pipe = fleet.PipelineLayer(layers=descs, loss_fn=nn.CrossEntropyLoss())
    model = fleet.distributed_model(pipe)
    assert type(model).__name__ == "PipelineParallel"
    opt = fleet.distributed_optimizer(
        paddle.optimizer.AdamW(0.01, parameters=pipe.parameters())
    )
    x = paddle.randn([8, 8])
    y = paddle.randint(0, 4, [8])
    losses = [float(model.train_batch((x, y), opt)) for _ in range(6)]
    assert losses[-1] < losses[0]
    # params of stage-1 layers live on the second stage's devices
    hcg = fleet.get_hybrid_communicate_group()
    stage1_layer = next(
        l for l in pipe.get_stage_items(1) if isinstance(l, nn.Linear)
    )
    devs = {d.id for d in stage1_layer.weight._value.sharding.device_set}
    expected = {d.id for d in np.asarray(hcg.get_stage_mesh(1).devices).ravel()}
    assert devs == expected


def test_pipeline_equals_serial():
    """pp=2 with microbatching == serial run on the same data/weights."""

    def run(pp):
        mesh_state.set_mesh(None)
        _init(pp=pp, acc_steps=4 if pp > 1 else 1)
        paddle.seed(5)
        descs = [
            fleet.LayerDesc(nn.Linear, 8, 16),
            fleet.LayerDesc(nn.ReLU),
            fleet.LayerDesc(nn.Linear, 16, 4),
        ]
        pipe = fleet.PipelineLayer(layers=descs, loss_fn=nn.CrossEntropyLoss())
        opt = paddle.optimizer.SGD(0.1, parameters=pipe.parameters())
        x = paddle.to_tensor(np.random.RandomState(2).randn(8, 8).astype(np.float32))
        y = paddle.to_tensor(np.arange(8) % 4)
        if pp > 1:
            model = fleet.distributed_model(pipe)
            for _ in range(3):
                loss = model.train_batch((x, y), opt)
        else:
            for _ in range(3):
                out = pipe(x)
                loss = nn.CrossEntropyLoss()(out, y)
                loss.backward()
                opt.step()
                opt.clear_grad()
        return [p.numpy().copy() for p in pipe.parameters()]

    params_pp = run(2)
    params_serial = run(1)
    for a, b in zip(params_pp, params_serial):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_sequence_parallel_linears():
    _init(mp=2)
    from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
        ColumnSequenceParallelLinear, RowSequenceParallelLinear, ScatterOp,
    )

    paddle.seed(0)
    col = ColumnSequenceParallelLinear(8, 16, has_bias=True)
    row = RowSequenceParallelLinear(16, 8, has_bias=True)
    x = paddle.randn([4, 2, 8])  # (seq, batch, hidden)
    xs = ScatterOp.apply(x)
    out = row(col(xs))
    ref = (
        x.numpy() @ col.weight.numpy() + col.bias.numpy()
    ) @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_recompute_matches_direct():
    paddle.seed(0)
    block = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 8))
    x = paddle.randn([4, 8])
    x.stop_gradient = False
    out1 = fleet.recompute(block, x)
    out1.sum().backward()
    g1 = x.grad.numpy().copy()
    w_g1 = block[0].weight.grad.numpy().copy()
    x.clear_grad()
    block[0].weight.clear_grad()
    out2 = block(x)
    out2.sum().backward()
    np.testing.assert_allclose(out1.numpy(), out2.numpy(), rtol=1e-5)
    np.testing.assert_allclose(g1, x.grad.numpy(), rtol=1e-5)
    np.testing.assert_allclose(w_g1, block[0].weight.grad.numpy(), rtol=1e-5)


def test_data_parallel_wrapper():
    _init(dp=8)
    m = paddle.DataParallel(nn.Linear(4, 2)) if hasattr(paddle, "DataParallel") else dist.DataParallel(nn.Linear(4, 2))
    x = paddle.randn([16, 4])
    out = m(x)
    assert out.shape == [16, 2]
    out.sum().backward()
    assert m._layers.weight.grad is not None


def test_collective_api_single_controller():
    dist.init_parallel_env()
    assert dist.get_world_size() == 1
    t = paddle.to_tensor([1.0, 2.0])
    out = dist.all_reduce(t)
    np.testing.assert_allclose(out.numpy(), [1, 2])
    gathered = []
    dist.all_gather(gathered, t)
    assert len(gathered) == 1
    dist.barrier()


def test_shard_tensor_api():
    from paddle_tpu.distributed.auto_parallel import (
        ProcessMesh, shard_tensor, Shard, Replicate,
    )

    mesh = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    t = paddle.ones([8, 4])
    st = shard_tensor(t, mesh, [Shard(0), Replicate()])
    spec = st._value.sharding.spec
    assert spec[0] == "x"
    np.testing.assert_allclose(st.numpy(), np.ones((8, 4)))


def test_dist_checkpoint_roundtrip(tmp_path):
    _init(sharding=4)
    from paddle_tpu.distributed.checkpoint import save_state_dict, load_state_dict
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    m = nn.Linear(8, 8)
    opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    group_sharded_parallel(m, opt, "p_g_os")
    w_ref = m.weight.numpy().copy()
    save_state_dict(m.state_dict(), str(tmp_path))
    m.weight.set_value(np.zeros_like(w_ref))
    load_state_dict(m.state_dict(), str(tmp_path))
    np.testing.assert_allclose(m.weight.numpy(), w_ref)
    # sharding preserved after load
    assert "sharding" in str(m.weight._value.sharding.spec)


def test_async_collective_task_handles():
    import paddle_tpu.distributed as dist

    x = paddle.to_tensor(np.ones(4, "f4"))
    task = dist.all_reduce(x, sync_op=False)
    assert hasattr(task, "wait") and task.wait() and task.is_completed()
    assert isinstance(dist.broadcast(x, src=0), type(x))  # sync returns tensor


def test_nan_check_fires_inside_jit():
    import jax
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.core import autograd

    set_flags({"FLAGS_check_nan_inf": True})
    try:
        def f(v):
            with autograd.no_grad():
                return Tensor(v, stop_gradient=True).log()._value

        with pytest.raises(Exception, match="NaN/Inf"):
            np.asarray(jax.jit(f)(np.array([-1.0], "f4")))
    finally:
        set_flags({"FLAGS_check_nan_inf": False})


def test_rpc_facade_local_and_nongoal_semantics():
    """paddle.distributed.rpc local semantics (the single-process fast
    path of the TCP implementation; cross-process coverage lives in
    test_launch_visualdl.test_two_process_rpc)."""
    import paddle_tpu.distributed.rpc as rpc

    info = rpc.init_rpc("worker0")
    assert rpc.get_current_worker_info() is info
    assert rpc.get_worker_info("worker0").name == "worker0"
    assert rpc.rpc_sync("worker0", lambda a, b: a + b, args=(2, 3)) == 5
    fut = rpc.rpc_async("worker0", lambda: 42)
    assert fut.result() == 42 and fut.wait() == 42
    with pytest.raises(RuntimeError, match="unknown rpc worker"):
        rpc.rpc_sync("elsewhere", lambda: None)
    rpc.shutdown()
    with pytest.raises(RuntimeError, match="init_rpc"):
        rpc.get_current_worker_info()
