"""The checked-in API manifests are the auditable form of COVERAGE.md's
surface claims (round-4 verdict #8): every name listed in
tests/manifests/*.txt must exist and be callable. Regenerate manifests
with scripts/gen_api_manifest.py when intentionally extending the
surface; anything that silently disappears fails here."""
import os

import pytest

import paddle_tpu as paddle

MANIFEST_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "manifests")

NAMESPACES = {
    "top_level.txt": lambda: paddle,
    "nn_functional.txt": lambda: paddle.nn.functional,
    "nn_layers.txt": lambda: paddle.nn,
    "linalg.txt": lambda: paddle.linalg,
    "fft.txt": lambda: paddle.fft,
    "sparse.txt": lambda: paddle.sparse,
    "incubate_functional.txt": lambda: paddle.incubate.nn.functional,
    "analysis.txt": lambda: __import__(
        "paddle_tpu.analysis", fromlist=["analysis"]),
    "serving.txt": lambda: __import__(
        "paddle_tpu.serving", fromlist=["serving"]),
    "obs.txt": lambda: __import__(
        "paddle_tpu.obs", fromlist=["obs"]),
}


def _names(fname):
    with open(os.path.join(MANIFEST_DIR, fname)) as f:
        return [ln.strip() for ln in f if ln.strip()]


@pytest.mark.parametrize("fname", sorted(NAMESPACES))
def test_manifest_names_present_and_callable(fname):
    ns = NAMESPACES[fname]()
    missing = [n for n in _names(fname)
               if not callable(getattr(ns, n, None))]
    assert not missing, (
        f"{fname}: {len(missing)} manifest names missing/not callable: "
        f"{missing[:10]}")


def test_manifest_counts_match_coverage_doc():
    """COVERAGE.md's surface numbers are generated, not hand-maintained:
    the doc must cite exactly the manifest sizes and the live registry
    count."""
    counts = {f: len(_names(f)) for f in NAMESPACES}
    doc = open(os.path.join(os.path.dirname(MANIFEST_DIR), os.pardir,
                            "COVERAGE.md")).read()
    for f, n in counts.items():
        token = f"{n} ({f.replace('.txt', '')} manifest)"
        assert token in doc, (
            f"COVERAGE.md out of date: expected the literal token "
            f"'{token}' — rerun scripts/gen_api_manifest.py and update")
    assert f"{len(paddle.OP_REGISTRY)} registry names" in doc, (
        f"COVERAGE.md registry count != {len(paddle.OP_REGISTRY)}")
