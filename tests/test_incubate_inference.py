"""incubate fused layers, MoE, generation, and the Predictor facade."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.parallel import mesh as mesh_state


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def test_fused_multi_transformer_decode_matches_full():
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    paddle.seed(0)
    fmt = FusedMultiTransformer(
        64, 4, 128, num_layers=3, norm_type="rmsnorm", activation="swiglu",
        num_key_value_heads=2)
    fmt.eval()
    x = paddle.randn([2, 8, 64])
    caches = fmt.gen_cache(2, 32)
    _, caches = fmt(x, caches=caches, time_step=0)
    nxt = paddle.randn([2, 1, 64])
    out_dec, caches = fmt(nxt, caches=caches, time_step=8)
    out_full = fmt(paddle.concat([x, nxt], axis=1))
    np.testing.assert_allclose(
        out_dec.numpy()[:, 0], out_full.numpy()[:, -1], atol=1e-4)


def test_fused_multi_transformer_gelu_layernorm():
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    paddle.seed(0)
    fmt = FusedMultiTransformer(32, 2, 64, num_layers=2)
    out = fmt(paddle.randn([2, 4, 32]))
    assert out.shape == [2, 4, 32]


def test_fused_functional_wrappers():
    from paddle_tpu.incubate.nn import functional as IF

    x = paddle.randn([2, 4, 8])
    w = paddle.ones([8])
    out = IF.fused_rms_norm(x, w)
    ref = F.rms_norm(x, w)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)
    out2, res = IF.fused_rms_norm(x, w, residual=paddle.zeros([2, 4, 8]))
    np.testing.assert_allclose(out2.numpy(), ref.numpy(), atol=1e-6)

    q, k, v = (paddle.randn([2, 6, 2, 32]) for _ in range(3))
    rq, rk, rv = IF.fused_rotary_position_embedding(q, k, v)
    assert rq.shape == q.shape and rk.shape == k.shape


def test_moe_layer_forward_backward():
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    paddle.seed(1)
    moe = MoELayer(16, 32, num_experts=4, gate="gshard")
    x = paddle.randn([4, 8, 16])
    x.stop_gradient = False
    y = moe(x)
    assert y.shape == [4, 8, 16]
    loss = (y * y).mean() + 0.01 * moe.l_aux
    loss.backward()
    assert float(paddle.abs(moe.gate_weight.grad).sum()) > 0
    assert float(paddle.abs(moe.w1.grad).sum()) > 0


def test_moe_capacity_drops_overflow():
    """switch gate with tiny capacity: tokens over capacity are dropped
    (output zero for them), never crash."""
    from paddle_tpu.incubate.distributed.models.moe import MoELayer, SwitchGate

    paddle.seed(2)
    moe = MoELayer(8, 16, num_experts=2, gate=SwitchGate(capacity_factor=0.5))
    y = moe(paddle.randn([16, 8]))
    assert y.shape == [16, 8]


def test_moe_expert_parallel_matches_serial():
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    from paddle_tpu.distributed import fleet

    x_np = np.random.RandomState(0).randn(8, 16).astype(np.float32)

    def run(parallel):
        mesh_state.set_mesh(None)
        if parallel:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {
                "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
                "sharding_degree": 1,
            }
            fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(3)
        moe = MoELayer(16, 32, num_experts=4, gate="gshard",
                       expert_axis="dp" if parallel else None)
        y = moe(paddle.to_tensor(x_np))
        return y.numpy(), float(moe.l_aux)

    yp, auxp = run(True)
    ys, auxs = run(False)
    np.testing.assert_allclose(yp, ys, rtol=1e-4, atol=1e-5)
    assert abs(auxp - auxs) < 1e-5


def test_generation_greedy_and_on_device():
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import greedy_search, generate_on_device

    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 8)))

    cur = ids.numpy()
    for _ in range(4):
        logits = m(paddle.to_tensor(cur))
        cur = np.concatenate(
            [cur, logits.numpy()[:, -1].argmax(-1)[:, None]], axis=1)

    out = greedy_search(m, ids, max_new_tokens=4)
    assert (out.numpy() == cur).all()
    out2 = generate_on_device(m, ids, max_new_tokens=4)
    assert (out2.numpy() == cur).all()


def test_generation_sampling_and_beam():
    """Round-5 decode strategies: sampling (top-k/top-p/temperature,
    seeded) and beam search, both whole-loop on-device. Oracles:
    top_k=1 sampling == greedy; num_beams=1 beam == greedy; a 4-beam
    search's best sequence log-prob (teacher-forced re-score) must be
    >= greedy's; sampling is seed-deterministic."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import (
        generate, generate_on_device, sampling_search, beam_search,
    )
    import jax.numpy as jnp
    import jax

    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 128, (2, 6)))
    new = 5

    greedy = generate_on_device(m, ids, max_new_tokens=new).numpy()

    # top_k=1 sampling degenerates to greedy regardless of seed
    s1 = sampling_search(m, ids, max_new_tokens=new, top_k=1, seed=3)
    assert (s1.numpy() == greedy).all()

    # seeded sampling is deterministic; different seeds eventually differ
    a = sampling_search(m, ids, max_new_tokens=new, temperature=2.0,
                        seed=0).numpy()
    b = sampling_search(m, ids, max_new_tokens=new, temperature=2.0,
                        seed=0).numpy()
    assert (a == b).all()
    c = sampling_search(m, ids, max_new_tokens=new, temperature=5.0,
                        seed=7).numpy()
    assert (c[:, :6] == greedy[:, :6]).all()  # prompt preserved

    # top_p very small keeps only the argmax token → greedy
    s2 = sampling_search(m, ids, max_new_tokens=new, top_p=1e-6, seed=9)
    assert (s2.numpy() == greedy).all()

    # beam with 1 beam == greedy
    b1, _ = beam_search(m, ids, max_new_tokens=new, num_beams=1)
    assert (b1.numpy() == greedy).all()

    def seq_logprob(tokens_np):
        """Teacher-forced log-prob of the generated suffix."""
        logits = m(paddle.to_tensor(tokens_np))._value
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tot = []
        for r in range(tokens_np.shape[0]):
            s = 0.0
            for t in range(6 - 1, tokens_np.shape[1] - 1):
                s += float(lp[r, t, tokens_np[r, t + 1]])
            tot.append(s)
        return np.asarray(tot)

    b4, scores4 = beam_search(m, ids, max_new_tokens=new, num_beams=4)
    b4_np = b4.numpy()
    assert (b4_np[:, :6] == greedy[:, :6]).all()
    lp_beam = seq_logprob(b4_np)
    lp_greedy = seq_logprob(greedy)
    assert (lp_beam >= lp_greedy - 1e-4).all(), (lp_beam, lp_greedy)
    # the reported cumulative scores match the teacher-forced re-score
    np.testing.assert_allclose(scores4.numpy(), lp_beam, rtol=1e-4,
                               atol=1e-4)

    # the facade routes
    g = generate(m, ids, max_new_tokens=new,
                 decode_strategy="beam_search", num_beams=4).numpy()
    assert (g == b4_np).all()


def test_generation_eos_padding_and_retirement():
    """eos handling on the on-device loops: once a row emits the eos
    token, every later position is pad (greedy + sampling), and a
    retired beam's score freezes (its padded continuation adds zero
    log-prob)."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import (
        generate_on_device, sampling_search, beam_search,
    )

    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(2).randint(0, 128, (2, 6)))
    new = 6

    plain = generate_on_device(m, ids, max_new_tokens=new).numpy()
    # pick the token row 0 greedily emits at step 1 as the "eos"
    eos = int(plain[0, 6 + 1])
    pad = 77
    out = generate_on_device(m, ids, max_new_tokens=new,
                             eos_token_id=eos, pad_token_id=pad).numpy()
    for r in range(out.shape[0]):
        gen = out[r, 6:]
        hits = np.nonzero(gen == eos)[0]
        if len(hits):
            after = gen[hits[0] + 1:]
            assert (after == pad).all(), (r, gen)
    # row 0 definitely hit it at step 1 → tail is all pad
    assert (out[0, 6 + 2:] == pad).all()
    # prefix up to and including eos matches the plain run
    assert (out[0, : 6 + 2] == plain[0, : 6 + 2]).all()

    # sampling honors eos the same way (top_k=1 = greedy path)
    s = sampling_search(m, ids, max_new_tokens=new, top_k=1,
                        eos_token_id=eos, pad_token_id=pad).numpy()
    assert (s == out).all()

    # beam: with eos, the best beam's reported score must equal the
    # teacher-forced log-prob of its tokens UP TO eos (frozen after)
    b4, scores = beam_search(m, ids, max_new_tokens=new, num_beams=3,
                             eos_token_id=eos, pad_token_id=pad)
    b4_np, scores_np = b4.numpy(), scores.numpy()
    import jax
    import jax.numpy as jnp

    logits = m(paddle.to_tensor(b4_np))._value
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    for r in range(b4_np.shape[0]):
        s_val = 0.0
        for t in range(5, b4_np.shape[1] - 1):
            nxt = b4_np[r, t + 1]
            s_val += float(lp[r, t, nxt])
            if nxt == eos:
                break
        np.testing.assert_allclose(scores_np[r], s_val, rtol=1e-4,
                                   atol=1e-4)


def test_predictor_roundtrip(tmp_path):
    import paddle_tpu.inference as infer
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
    net.eval()
    path = os.path.join(str(tmp_path), "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8], "float32")])

    config = infer.Config(path)
    config.enable_memory_optim()  # accepted + recorded, not an error
    pred = infer.create_predictor(config)
    names = pred.get_input_names()
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    pred.get_input_handle(names[0]).copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_global_scatter_facade():
    import paddle_tpu.distributed.utils as du

    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(6, 2))
    lc = paddle.to_tensor(np.array([2, 4]))
    gc = paddle.to_tensor(np.array([2, 4]))
    out = du.global_scatter(x, lc, gc)
    np.testing.assert_allclose(out.numpy(), x.numpy())
    with pytest.raises(ValueError):
        du.global_scatter(x, lc, paddle.to_tensor(np.array([4, 2])))


def test_masked_multihead_attention_oracle():
    import math
    from paddle_tpu.incubate.nn.functional import masked_multihead_attention

    rng = np.random.RandomState(0)
    B, H, HK, D, S = 2, 4, 2, 16, 8
    q = paddle.to_tensor(rng.randn(B, H, D).astype("f4"))
    kc = rng.randn(B, S, HK, D).astype("f4")
    vc = rng.randn(B, S, HK, D).astype("f4")
    ckv = paddle.to_tensor(np.stack([kc, vc]))
    lens = np.array([5, 8], "i4")
    out = masked_multihead_attention(
        q, ckv, sequence_lengths=paddle.to_tensor(lens))
    kr = np.repeat(kc, 2, axis=2)
    vr = np.repeat(vc, 2, axis=2)
    sc = 1 / math.sqrt(D)
    for b in range(B):
        L = lens[b]
        logits = np.einsum(
            "hd,khd->hk", np.asarray(q._value)[b], kr[b, :L]) * sc
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hk,khd->hd", p, vr[b, :L])
        np.testing.assert_allclose(
            np.asarray(out._value)[b], ref, rtol=1e-4, atol=1e-4)


def test_masked_multihead_attention_src_mask_and_validation():
    from paddle_tpu.incubate.nn.functional import masked_multihead_attention

    rng = np.random.RandomState(1)
    B, H, HK, D, S = 1, 2, 2, 8, 4
    q = paddle.to_tensor(rng.randn(B, H, D).astype("f4"))
    ckv = paddle.to_tensor(rng.randn(2, B, S, HK, D).astype("f4"))
    lens = paddle.to_tensor(np.array([S], "i4"))
    # a -inf bias on position 0 must shut that key off
    bias = np.zeros((B, 1, 1, S), "f4")
    bias[..., 0] = -1e30
    out_masked = masked_multihead_attention(
        q, ckv, src_mask=paddle.to_tensor(bias), sequence_lengths=lens)
    lens3 = paddle.to_tensor(np.array([S], "i4"))
    # equivalent: shorten cache from the front is not expressible; just
    # check it differs from the unmasked result and is finite
    out_plain = masked_multihead_attention(q, ckv, sequence_lengths=lens3)
    assert not np.allclose(
        np.asarray(out_masked._value), np.asarray(out_plain._value))
    assert np.isfinite(np.asarray(out_masked._value)).all()

    with pytest.raises(ValueError, match="requires"):
        masked_multihead_attention(q)
    # round-5: out_scale is a supported a8w8 epilogue — int8 out,
    # clip(round(out / out_scale)) (full parity test lives in
    # test_paged_attention.test_masked_mha_out_scale_quant)
    out_q8 = masked_multihead_attention(
        q, ckv, sequence_lengths=lens, out_scale=0.5)
    assert str(out_q8._value.dtype) == "int8"


def test_predictor_exact_inputs_and_clone_isolation(tmp_path):
    """Round-2 weak #8: input count is recorded in the artifact (no
    heuristics — a 2-input model exposes exactly 2 handles) and clone()
    gives independent handles over the shared compiled program."""
    import paddle_tpu.inference as infer
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(8, 4)

        def forward(self, a, b):
            return self.lin(a) + self.lin(b)

    paddle.seed(0)
    net = TwoIn()
    net.eval()
    path = os.path.join(str(tmp_path), "twoin")
    paddle.jit.save(net, path, input_spec=[
        InputSpec([2, 8], "float32"), InputSpec([2, 8], "float32")])

    pred = infer.create_predictor(infer.Config(path))
    names = pred.get_input_names()
    assert len(names) == 2, names

    rng = np.random.RandomState(0)
    a, b = rng.randn(2, 8).astype("f4"), rng.randn(2, 8).astype("f4")
    pred.get_input_handle(names[0]).copy_from_cpu(a)
    pred.get_input_handle(names[1]).copy_from_cpu(b)

    clone = pred.clone()
    assert clone._layer is pred._layer  # compiled program shared
    # clone handles are fresh: not the same objects, no inherited data
    for n in names:
        assert clone.get_input_handle(n) is not pred.get_input_handle(n)
        assert clone.get_input_handle(n)._value is None

    # fill the clone with different data; both must produce their own
    a2, b2 = rng.randn(2, 8).astype("f4"), rng.randn(2, 8).astype("f4")
    clone.get_input_handle(names[0]).copy_from_cpu(a2)
    clone.get_input_handle(names[1]).copy_from_cpu(b2)
    pred.run()
    clone.run()
    out1 = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    out2 = clone.get_output_handle(clone.get_output_names()[0]).copy_to_cpu()
    ref1 = net(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
    ref2 = net(paddle.to_tensor(a2), paddle.to_tensor(b2)).numpy()
    np.testing.assert_allclose(out1, ref1, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out2, ref2, rtol=1e-5, atol=1e-5)


def _moe_run(dispatch_mode, capacity_factor=2.0, seed=5):
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    mesh_state.set_mesh(None)
    paddle.seed(seed)
    moe = MoELayer(16, 32, num_experts=4, gate="gshard",
                   capacity_factor=capacity_factor, activation="swiglu",
                   dispatch_mode=dispatch_mode)
    x = paddle.to_tensor(
        np.random.RandomState(7).randn(6, 8, 16).astype(np.float32))
    x.stop_gradient = False
    y = moe(x)
    loss = (y * y).mean() + 0.01 * moe.l_aux
    loss.backward()
    return (y.numpy(), float(moe.l_aux),
            {n: p.grad.numpy() for n, p in moe.named_parameters()})


def test_moe_grouped_matches_einsum_dispatch():
    """Round-4 perf tier: the sort/ragged_dot grouped dispatch must be
    numerically identical (fwd, aux, ALL grads) to the dense GShard
    einsum tier — same gate, same capacity semantics."""
    yg, auxg, gg = _moe_run("grouped")
    ye, auxe, ge = _moe_run("einsum")
    np.testing.assert_allclose(yg, ye, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(auxg, auxe, rtol=1e-5)
    for n in ge:
        np.testing.assert_allclose(
            gg[n], ge[n], rtol=2e-4, atol=1e-5, err_msg=n)


def test_moe_grouped_capacity_drop_matches_einsum():
    """Under capacity pressure (factor 0.5, tokens dropped) both tiers
    must drop the SAME tokens: round-major queue order parity."""
    yg, auxg, _ = _moe_run("grouped", capacity_factor=0.5)
    ye, auxe, _ = _moe_run("einsum", capacity_factor=0.5)
    np.testing.assert_allclose(yg, ye, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(auxg, auxe, rtol=1e-5)
    # capacity must have GENUINELY dropped tokens at factor 0.5 — the
    # queue-order parity this test pins is vacuous otherwise
    yg_roomy, _, _ = _moe_run("grouped", capacity_factor=2.0)
    assert np.abs(yg - yg_roomy).max() > 1e-6, \
        "capacity_factor=0.5 dropped nothing; test is vacuous"


def _moe_ep_run(dispatch_mode, capacity_factor=2.0, seed=5):
    """Grouped/einsum run on a dp=4 x mp=2 mesh with dp expert sharding."""
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    from paddle_tpu.distributed import fleet

    mesh_state.set_mesh(None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
        "sharding_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    moe = MoELayer(16, 32, num_experts=4, gate="gshard",
                   capacity_factor=capacity_factor, activation="swiglu",
                   expert_axis="dp", dispatch_mode=dispatch_mode)
    x = paddle.to_tensor(
        np.random.RandomState(7).randn(6, 8, 16).astype(np.float32))
    x.stop_gradient = False
    y = moe(x)
    loss = (y * y).mean() + 0.01 * moe.l_aux
    loss.backward()
    out = (y.numpy(), float(moe.l_aux),
           {n: p.grad.numpy() for n, p in moe.named_parameters()})
    mesh_state.set_mesh(None)
    return out


@pytest.mark.slow  # heaviest test in tier-1 (~30s: 4 EP/serial runs
# x 2 capacity factors under an 8-device mesh); the plain EP-vs-serial
# parity above keeps the shard_map path covered in-budget — the 870s
# tier-1 ceiling forced a re-tier as the suite grew (PR 7)
def test_moe_grouped_expert_parallel_matches_serial():
    """Round-5 (verdict #5): the grouped ragged_dot tier now runs
    EP-SHARDED (shard_map: global gate + per-shard ragged_dot +
    psum_scatter combine) and must match the mesh-less serial grouped
    tier exactly — fwd, aux, ALL grads — including under capacity
    pressure (the drop set is a global-queue decision the EP schedule
    must reproduce)."""
    for cf in (2.0, 0.5):
        ye, auxe, ge = _moe_ep_run("grouped", capacity_factor=cf)
        ys, auxs, gs = _moe_run("grouped", capacity_factor=cf)
        np.testing.assert_allclose(ye, ys, rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(auxe, auxs, rtol=1e-5)
        for n in gs:
            np.testing.assert_allclose(
                ge[n], gs[n], rtol=2e-4, atol=1e-5, err_msg=f"cf={cf} {n}")


def test_moe_grouped_ep_rejects_non_divisible_experts():
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    from paddle_tpu.distributed import fleet

    mesh_state.set_mesh(None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
        "sharding_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    with pytest.raises(ValueError):
        MoELayer(16, 32, num_experts=6, expert_axis="dp",
                 dispatch_mode="grouped")
    mesh_state.set_mesh(None)


def test_fused_multi_transformer_weight_only_int8_parity():
    """Round-4 verdict #5: the int8 fused_multi_transformer variant.
    quantize_weight_only() output must EXACTLY match a float FMT whose
    weights are the dequantized (int8 * scale) values — proving the
    serving stack consumes the artifact with no wiring error. Prefill
    AND decode; int8 weights must actually live in HBM as int8."""
    import jax.numpy as jnp
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    def build():
        paddle.seed(4)
        return FusedMultiTransformer(
            64, 4, 128, num_layers=3, norm_type="rmsnorm",
            activation="swiglu", num_key_value_heads=2).eval()

    fmt_q = build().quantize_weight_only()
    assert fmt_q.qkv_weight._value.dtype == jnp.int8
    fmt_ref = build()
    # install the dequantized weights into the float reference
    for name in ("qkv_weight", "linear_weight", "ffn1_weight",
                 "ffn2_weight"):
        q = getattr(fmt_q, name)._value.astype(jnp.float32)
        s = getattr(fmt_q, name + "_scale")._value
        getattr(fmt_ref, name).set_value(paddle.Tensor(q * s[:, None, :]))

    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 8, 64).astype("f4"))
    cq = fmt_q.gen_cache(2, 32)
    cr = fmt_ref.gen_cache(2, 32)
    out_q, cq = fmt_q(x, caches=cq, time_step=0)
    out_r, cr = fmt_ref(x, caches=cr, time_step=0)
    np.testing.assert_allclose(
        np.asarray(out_q._value), np.asarray(out_r._value),
        rtol=1e-5, atol=1e-5)
    nxt = paddle.to_tensor(
        np.random.RandomState(1).randn(2, 1, 64).astype("f4"))
    dq, _ = fmt_q(nxt, caches=cq, time_step=8)
    dr, _ = fmt_ref(nxt, caches=cr, time_step=8)
    np.testing.assert_allclose(
        np.asarray(dq._value), np.asarray(dr._value),
        rtol=1e-5, atol=1e-5)
    # and the quant error vs the ORIGINAL float weights is small but
    # nonzero (guards against accidentally storing float weights)
    fmt_f = build()
    cf = fmt_f.gen_cache(2, 32)
    out_f, _ = fmt_f(x, caches=cf, time_step=0)
    diff = np.abs(np.asarray(out_q._value) - np.asarray(out_f._value))
    assert 0 < diff.max() < 0.1


def test_sliding_window_rolling_cache_decode():
    """Round-5: windowed models decode against a ROLLING KV buffer of
    window length. Oracle: on-device greedy decode == step-by-step full
    forwards through the same model (whose dense path uses banded
    sliding-window attention), across the point where the buffer wraps.
    Also: init_caches clamps to the window."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import greedy_search, generate_on_device

    paddle.seed(0)
    w = 8
    m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False,
                                          sliding_window=w))
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(4).randint(0, 128, (2, 10)))
    new = 7  # crosses the wrap point (10 prompt > window 8 already)

    # dense reference: full forward each step; banded attention inside
    cur = ids.numpy()
    for _ in range(new):
        logits = m(paddle.to_tensor(cur))
        cur = np.concatenate(
            [cur, logits.numpy()[:, -1].argmax(-1)[:, None]], axis=1)

    out = generate_on_device(m, ids, max_new_tokens=new)
    assert (out.numpy() == cur).all(), (out.numpy(), cur)

    host = greedy_search(m, ids, max_new_tokens=new)
    assert (host.numpy() == cur).all()

    caches = m.init_caches(2, 64)
    assert caches[0][0].shape[1] == w  # clamped to the window


def test_speculative_greedy_matches_target_greedy():
    """Speculative decode must emit EXACTLY the target's greedy tokens,
    for a same-as-target draft (everything accepted) and an independent
    draft (frequent rejections + corrections)."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import (
        generate_on_device, speculative_greedy_search,
    )

    paddle.seed(0)
    target = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    target.eval()
    paddle.seed(123)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        tensor_parallel=False, num_hidden_layers=1, hidden_size=32,
        intermediate_size=64, num_attention_heads=2,
        num_key_value_heads=1))
    draft.eval()
    ids = paddle.to_tensor(np.random.RandomState(5).randint(0, 128, (1, 7)))
    new = 9

    ref = generate_on_device(target, ids, max_new_tokens=new).numpy()

    out, rate = speculative_greedy_search(target, draft, ids,
                                          max_new_tokens=new, gamma=3)
    assert (out.numpy() == ref).all(), (out.numpy(), ref)
    assert 0.0 <= rate <= 1.0

    # draft == target: every proposal accepted
    out2, rate2 = speculative_greedy_search(target, target, ids,
                                            max_new_tokens=new, gamma=3)
    assert (out2.numpy() == ref).all()
    # not exactly 1.0: the one-shot verify forward and the step-wise
    # draft loop reassociate differently in fp, which can flip argmax
    # ties on an UNTRAINED near-uniform model; high acceptance is the
    # honest invariant
    assert rate2 >= 0.5, rate2

    with pytest.raises(ValueError, match="batch 1"):
        speculative_greedy_search(
            target, draft,
            paddle.to_tensor(np.zeros((2, 4), np.int32)), 4)


def test_speculative_full_accept_keeps_draft_cache_complete():
    """ADVICE round-5 medium: after a FULL-accept round (a == g) the
    draft must still consume props[g-1] — without the extra forward the
    slot at pos+g stays stale forever and every later draft forward
    attends a hole in the accepted history. A recording proxy around
    the draft asserts every generated position < the final draft write
    position was fed exactly the emitted token."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import speculative_greedy_search

    paddle.seed(0)
    target = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    target.eval()

    class RecordingDraft:
        """Wraps the draft model; records (token, position) per
        single-token forward."""

        def __init__(self, m):
            self._m = m
            self.writes = {}  # position -> last token fed there

        @property
        def config(self):
            return self._m.config

        def init_caches(self, *a, **kw):
            return self._m.init_caches(*a, **kw)

        def __call__(self, ids, caches=None, position_offset=0):
            arr = np.asarray(ids._value)
            for j in range(arr.shape[1]):
                self.writes[int(position_offset) + j] = int(arr[0, j])
            return self._m(ids, caches=caches,
                           position_offset=position_offset)

    # draft == target maximizes full-accept rounds (the bug's trigger)
    draft = RecordingDraft(target)
    ids = paddle.to_tensor(np.random.RandomState(5).randint(0, 128, (1, 7)))
    new = 9
    out, rate = speculative_greedy_search(target, draft, ids,
                                          max_new_tokens=new, gamma=3)
    assert rate > 0.5  # the scenario really exercised full accepts
    toks = [int(t) for t in out.numpy()[0]]

    # the draft cache must hold the COMPLETE accepted history: every
    # position from the prompt end up to its last write was fed, and
    # fed the token the search actually emitted at that position
    s_in = ids.shape[1]
    last = max(p for p in draft.writes if p >= s_in)
    missing = [p for p in range(s_in, last + 1)
               if p not in draft.writes]
    assert not missing, f"stale draft-KV slots at positions {missing}"
    wrong = {p: (draft.writes[p], toks[p])
             for p in range(s_in, min(last + 1, len(toks)))
             if draft.writes[p] != toks[p]}
    assert not wrong, f"draft cache tokens diverge from emitted: {wrong}"
