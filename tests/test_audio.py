"""paddle.audio features vs manual DSP oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.audio import functional as AF
from paddle_tpu.audio import Spectrogram, MelSpectrogram, MFCC


def test_windows():
    w = np.asarray(AF.get_window("hann", 64)._value)
    np.testing.assert_allclose(
        w, 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(64) / 64), rtol=1e-5)
    assert np.asarray(AF.get_window("hamming", 32)._value).shape == (32,)


def test_mel_scale_roundtrip():
    for htk in (False, True):
        hz = 440.0
        back = AF.mel_to_hz(AF.hz_to_mel(hz, htk), htk)
        np.testing.assert_allclose(back, hz, rtol=1e-4)


def test_fbank_shape_and_coverage():
    fb = np.asarray(AF.compute_fbank_matrix(16000, 512, n_mels=40)._value)
    assert fb.shape == (40, 257)
    assert (fb >= 0).all() and fb.sum() > 0


def test_spectrogram_matches_manual():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2048).astype("f4")
    layer = Spectrogram(n_fft=256, hop_length=128, center=False)
    out = np.asarray(layer(paddle.to_tensor(x))._value)
    w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(256) / 256)
    ref0 = np.abs(np.fft.rfft(x[0, :256] * w)) ** 2
    np.testing.assert_allclose(out[0, :, 0], ref0, rtol=1e-2, atol=1e-2)


def test_mel_and_mfcc_shapes():
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(2, 4096).astype("f4"))
    mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert mel.shape[0:2] == [2, 40]
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
    assert mfcc.shape[0:2] == [2, 13]
    assert np.isfinite(np.asarray(mfcc._value)).all()


def test_power_to_db():
    x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], "f4"))
    db = np.asarray(AF.power_to_db(x, top_db=None)._value)
    np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-4)
