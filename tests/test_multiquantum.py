"""Multi-quantum on-device decode driver (ISSUE 17): the K-quanta
``lax.while_loop`` driver must be BIT-EXACT vs the per-quantum engine
across the whole serving matrix — greedy, fixed-seed sampling,
speculative rounds (where K is deliberately ignored: acceptance counts
live on the host), prefix-cache hits with copy-on-write, int8
weights + int8 KV, and mid-run preemption — because between
steady-state quanta the host only round-trips device state through
untouched int32 mirrors, so folding K round-trips on-device changes no
math. The fused online-softmax paged-attention path gets the same
oracle treatment (engine-level stream equality plus a tensor-level
unit parity check vs the XLA-gather reference), the
``Scheduler.steady_state`` predicate that gates K is unit-tested, the
K-token dispatch must account K quanta (token attribution conserved),
the ``serving_host_gap_fraction`` gauge must be live, and the
``serving_multiquantum_step`` recipe budget + golden pin the compiled
driver (zero host callbacks, pools donated)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


def _ragged(cfg, rng, n=5, p_lens=(5, 9, 3, 12, 7),
            max_new=(9, 6, 11, 7, 8)):
    prompts = [rng.randint(1, cfg.vocab_size, p).astype(np.int32)
               for p in p_lens[:n]]
    return list(zip(prompts, max_new[:n]))


def _run_streams(engine, requests, seeds=None):
    reqs = [engine.submit(p, max_new_tokens=mn,
                          seed=0 if seeds is None else seeds[i])
            for i, (p, mn) in enumerate(requests)]
    engine.run()
    return [list(map(int, engine.output_tokens(r))) for r in reqs]


# ------------------------------------------------- bit-exactness matrix
def test_multiquantum_greedy_matrix(tiny_model):
    """Greedy ragged requests over 2 slots (retirement + slot reuse
    mid-run): K=4 and K=4+fused streams bit-exact vs the per-quantum
    gather engine, and the fused path alone (K=1) as well — the driver
    and the attention rewrite are independently stream-preserving."""
    cfg, model = tiny_model
    rng = np.random.RandomState(0)
    requests = _ragged(cfg, rng)
    kw = dict(num_slots=2, block_size=4, prefill_chunk=4,
              decode_quantum=3)
    base = _run_streams(ServingEngine(model, **kw), requests)
    for mq, attn in ((4, "gather"), (1, "fused"), (4, "fused")):
        got = _run_streams(
            ServingEngine(model, multi_quantum=mq, attn_impl=attn,
                          **kw), requests)
        assert got == base, f"stream drift at K={mq} attn={attn}"


def test_multiquantum_sampling_fixed_seed(tiny_model):
    """Fixed-seed per-request sampling: the K=4 driver replays the
    per-quantum sampling engine bit-for-bit (the per-slot PRNG fold-in
    is part of the carried on-device state)."""
    cfg, model = tiny_model
    rng = np.random.RandomState(1)
    requests = _ragged(cfg, rng)
    seeds = [3, 1, 4, 1, 5]
    kw = dict(num_slots=2, block_size=4, prefill_chunk=4,
              decode_quantum=3, decode_strategy="sampling",
              temperature=0.8, top_k=8)
    base = _run_streams(ServingEngine(model, **kw), requests, seeds)
    got = _run_streams(ServingEngine(model, multi_quantum=4, **kw),
                       requests, seeds)
    assert got == base


def test_multiquantum_spec_round_ignores_k(tiny_model):
    """Speculative engines deliberately DON'T build the K-quanta
    driver — acceptance counts must cross the host every round — so
    ``multi_quantum`` is silently inert there and the streams are
    trivially identical to the per-round spec engine."""
    cfg, model = tiny_model
    paddle.seed(11)
    draft = LlamaForCausalLM(
        LlamaConfig.tiny(tensor_parallel=False, num_hidden_layers=1))
    draft.eval()
    rng = np.random.RandomState(2)
    requests = _ragged(cfg, rng, n=3)
    kw = dict(num_slots=2, block_size=4, prefill_chunk=4,
              spec_draft=draft, spec_gamma=3)
    base = _run_streams(ServingEngine(model, **kw), requests)
    mq_eng = ServingEngine(model, multi_quantum=4, **kw)
    assert mq_eng._mq_quantum is None  # never built for spec engines
    assert _run_streams(mq_eng, requests) == base


def test_multiquantum_prefix_hit_cow(tiny_model):
    """Prefix-cache hits + copy-on-write under the K driver: shared
    system prompt across requests (one request is the BARE prompt, so
    its capped re-prefill lands in a shared block and COW fires) —
    streams bit-exact vs the per-quantum prefix engine, with real
    cache hits in both arms."""
    cfg, model = tiny_model
    rng = np.random.RandomState(3)
    sys_prompt = rng.randint(1, cfg.vocab_size, 8).astype(np.int32)
    requests = [
        (np.concatenate([sys_prompt,
                         rng.randint(1, cfg.vocab_size, t)
                         .astype(np.int32)]), mn)
        for t, mn in ((3, 8), (5, 6), (2, 9))
    ] + [(sys_prompt.copy(), 7)]
    kw = dict(num_slots=2, block_size=4, prefill_chunk=4,
              decode_quantum=3, prefix_cache=True)

    def arm(mq):
        eng = ServingEngine(model, multi_quantum=mq, **kw)
        streams = _run_streams(eng, requests)
        stats = eng.pool.prefix_cache_stats()
        assert stats["hits"] > 0, "the hit path must actually run"
        return streams

    assert arm(4) == arm(1)


def test_multiquantum_int8(tiny_model):
    """int8 weights + int8 KV pool under the K driver and the fused
    dequant attention: streams bit-exact vs the per-quantum int8
    gather engine (fresh models per arm — quantization sweeps the
    params in place)."""
    cfg, _ = tiny_model
    rng = np.random.RandomState(4)
    requests = _ragged(cfg, rng, n=4)
    kw = dict(num_slots=2, block_size=4, prefill_chunk=4,
              decode_quantum=3, quantize="weight_only_int8",
              kv_dtype="int8")

    def arm(mq, attn):
        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny(
            tensor_parallel=False))
        return _run_streams(
            ServingEngine(model, multi_quantum=mq, attn_impl=attn,
                          **kw), requests)

    base = arm(1, "gather")
    assert arm(4, "gather") == base
    assert arm(4, "fused") == base


def test_multiquantum_preemption(tiny_model):
    """Mid-run preemption: evict a request while the K=4 engine is
    decoding, resume via re-prefill — the stream must still be
    bit-exact vs the per-quantum engine given the same eviction (a
    preempted slot forces admission churn, so the driver must fall
    back to K=1 until steady state returns)."""
    cfg, model = tiny_model
    rng = np.random.RandomState(5)
    requests = _ragged(cfg, rng, n=4, p_lens=(5, 9, 3, 12),
                       max_new=(16, 12, 14, 10))
    kw = dict(num_slots=2, block_size=4, prefill_chunk=4,
              decode_quantum=3)

    def arm(mq):
        eng = ServingEngine(model, multi_quantum=mq, **kw)
        reqs = [eng.submit(p, max_new_tokens=mn)
                for p, mn in requests]
        while len(reqs[0].tokens) < 2:
            eng.step()
        assert not reqs[0].finished
        eng.preempt(reqs[0])
        eng.run()
        return [list(map(int, eng.output_tokens(r))) for r in reqs]

    assert arm(4) == arm(1)


# ------------------------------------------- scheduling + accounting
def test_steady_state_predicate(tiny_model):
    """``Scheduler.steady_state()`` — the K gate — is True exactly
    when the batch composition cannot change before the next dispatch:
    no waiting requests, no slot mid-prefill, at least one decoding."""
    cfg, model = tiny_model
    eng = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=4, decode_quantum=3)
    sched = eng.scheduler
    assert not sched.steady_state()  # idle: nothing decoding
    rng = np.random.RandomState(6)
    r0 = eng.submit(rng.randint(1, cfg.vocab_size, 6).astype(np.int32),
                    max_new_tokens=12)
    assert not sched.steady_state()  # waiting for admission
    while sched.waiting or sched.prefilling():
        eng.step()
    assert sched.steady_state()      # one slot, pure decode
    eng.submit(rng.randint(1, cfg.vocab_size, 6).astype(np.int32),
               max_new_tokens=4)
    assert not sched.steady_state()  # admission pending again
    eng.run()
    assert not sched.steady_state()  # drained
    assert r0.finished


def test_multiquantum_accounting_conserved(tiny_model):
    """A K-token dispatch is accounted as K quanta: with K=4 live the
    engine retires more decode quanta than it takes host steps, and
    token attribution stays conserved — every emitted token lands in
    the registry exactly once (the obs/attribution seams see K
    sub-quanta, not one fat quantum)."""
    cfg, model = tiny_model
    rng = np.random.RandomState(7)
    eng = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        multi_quantum=4)
    reqs = [eng.submit(rng.randint(1, cfg.vocab_size, 5)
                       .astype(np.int32), max_new_tokens=24)
            for _ in range(2)]
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
    assert eng.stats["decode_quanta"] > steps, \
        "K>1 folding never engaged"
    emitted = sum(len(r.tokens) for r in reqs)
    assert int(eng.obs.registry.get(
        "serving_tokens_emitted_total").value()) == emitted


def test_host_gap_gauge_live(tiny_model):
    """The decode collect half feeds the dispatch-boundary host-gap
    gauge: after a run the fraction is a sane [0, 1) value on the
    /metrics surface."""
    cfg, model = tiny_model
    rng = np.random.RandomState(8)
    eng = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        multi_quantum=4)
    eng.submit(rng.randint(1, cfg.vocab_size, 6).astype(np.int32),
               max_new_tokens=12)
    eng.run()
    g = eng.obs.registry.get("serving_host_gap_fraction")
    assert 0.0 <= g.value() < 1.0
    text = eng.obs.registry.prometheus()
    assert "serving_host_gap_fraction" in text


# ---------------------------------------------- fused attention unit
def test_fused_attention_matches_gather_unit():
    """Tensor-level parity: the online-softmax block-streaming
    attention equals the XLA-gather reference on random pools with
    ragged lengths and dead rows (lens carries the alive mask), in
    f32 to tight tolerance and bit-exactly after the bf16 output cast
    the decode quantum applies."""
    import jax.numpy as jnp

    from paddle_tpu.serving.engine import (
        _fused_paged_decode_attn, _xla_paged_decode_attn)

    rng = np.random.RandomState(9)
    S, w, bs, hq, hk, d, B = 4, 5, 4, 4, 2, 16, 24
    q = jnp.asarray(rng.randn(S, hq, d).astype(np.float32))
    kp = jnp.asarray(rng.randn(B, bs, hk, d).astype(np.float32))
    vp = jnp.asarray(rng.randn(B, bs, hk, d).astype(np.float32))
    tables = jnp.asarray(
        rng.randint(0, B, (S, w)).astype(np.int32))
    lens = jnp.asarray(np.array([7, 20, 1, 13], dtype=np.int32))
    ref = _xla_paged_decode_attn(q, kp, vp, tables, lens)
    got = _fused_paged_decode_attn(q, kp, vp, tables, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)
    qb = q.astype(jnp.bfloat16)
    ref_b = _xla_paged_decode_attn(qb, kp.astype(jnp.bfloat16),
                                   vp.astype(jnp.bfloat16), tables,
                                   lens)
    got_b = _fused_paged_decode_attn(qb, kp.astype(jnp.bfloat16),
                                     vp.astype(jnp.bfloat16), tables,
                                     lens)
    assert np.array_equal(
        np.asarray(got_b).view(np.uint16),
        np.asarray(ref_b).view(np.uint16)), \
        "bf16 outputs must be bit-identical"


def test_fused_attention_int8_pools_unit():
    """Same parity with int8 K/V pools + per-row f32 scale pools (the
    fused path dequantizes per streamed block)."""
    import jax.numpy as jnp

    from paddle_tpu.serving.engine import (
        _fused_paged_decode_attn, _xla_paged_decode_attn)

    rng = np.random.RandomState(10)
    S, w, bs, hq, hk, d, B = 3, 4, 4, 4, 2, 8, 16
    q = jnp.asarray(rng.randn(S, hq, d).astype(np.float32))
    kq = jnp.asarray(rng.randint(-127, 128, (B, bs, hk, d))
                     .astype(np.int8))
    vq = jnp.asarray(rng.randint(-127, 128, (B, bs, hk, d))
                     .astype(np.int8))
    ks = jnp.asarray((rng.rand(B, bs, hk) * 0.02 + 1e-3)
                     .astype(np.float32))
    vs = jnp.asarray((rng.rand(B, bs, hk) * 0.02 + 1e-3)
                     .astype(np.float32))
    tables = jnp.asarray(rng.randint(0, B, (S, w)).astype(np.int32))
    lens = jnp.asarray(np.array([5, 16, 2], dtype=np.int32))
    ref = _xla_paged_decode_attn(q, kq, vq, tables, lens, ks=ks, vs=vs)
    got = _fused_paged_decode_attn(q, kq, vq, tables, lens,
                                   ks=ks, vs=vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


# -------------------------------------------------- recipe budget gate
def test_serving_multiquantum_step_budget():
    """ISSUE 17 acceptance: the EXACT K=4 while-loop driver the
    multi-quantum engine dispatches (fused attention live) has zero
    host callbacks, zero involuntary remat, no collectives, every KV
    pool leaf donated — and its golden fingerprint matches, while the
    K=1 engines' goldens stay untouched (their tests compare against
    the same checked-in files as before)."""
    from paddle_tpu import analysis

    report = analysis.run_recipe("serving_multiquantum_step")
    assert len(report.remat_events) == 0
    assert report.host_sync is not None and report.host_sync.count == 0
    assert report.total_collectives == 0
    assert report.donation.undonated() == []
    assert report.memory.temp_bytes is not None
    analysis.check_recipe_fingerprint("serving_multiquantum_step",
                                      report)


def test_multiquantum_rejects_bad_args(tiny_model):
    cfg, model = tiny_model
    with pytest.raises(ValueError):
        ServingEngine(model, multi_quantum=0)
    with pytest.raises(ValueError):
        ServingEngine(model, attn_impl="flash")
    eng = ServingEngine(model, num_slots=2, block_size=4)
    with pytest.raises(ValueError):
        eng.multiquantum_step_target()  # K=1 engine has no mq program
