"""Native C++ io core (csrc/paddle_tpu_io.cc) — gather/shuffle/pack via
ctypes, plus the DataLoader TensorDataset fast path."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, TensorDataset, pack_varlen
from paddle_tpu.io import _native


requires_native = pytest.mark.skipif(
    _native.lib() is None, reason="native io core not built (no g++?)"
)


@requires_native
def test_gather_rows_matches_numpy():
    rng = np.random.RandomState(0)
    src = np.ascontiguousarray(rng.randn(128, 17, 3).astype("f4"))
    idx = rng.randint(0, 128, 50)
    np.testing.assert_array_equal(
        _native.gather_rows(src, idx), src[idx]
    )


@requires_native
def test_gather_rows_bounds_check():
    src = np.zeros((4, 2), "f4")
    with pytest.raises(IndexError):
        _native.gather_rows(src, np.array([0, 9]))


@requires_native
def test_shuffle_indices_deterministic_permutation():
    a = _native.shuffle_indices(1000, seed=42)
    b = _native.shuffle_indices(1000, seed=42)
    c = _native.shuffle_indices(1000, seed=43)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    np.testing.assert_array_equal(np.sort(a), np.arange(1000))


def test_pack_varlen_pads_and_truncates():
    rows = [[1, 2, 3], [4], [5, 6, 7, 8, 9]]
    out, lengths = pack_varlen(rows, max_len=4, pad_id=-1)
    np.testing.assert_array_equal(
        np.asarray(out._value),
        [[1, 2, 3, -1], [4, -1, -1, -1], [5, 6, 7, 8]],
    )
    np.testing.assert_array_equal(np.asarray(lengths._value), [3, 1, 4])


@requires_native
def test_dataloader_native_fast_path_matches_python():
    rng = np.random.RandomState(1)
    x = rng.randn(64, 8).astype("f4")
    y = rng.randint(0, 4, 64).astype("i8")
    ds = TensorDataset([x, y])
    dl = DataLoader(ds, batch_size=16, shuffle=False)
    assert dl._use_native_fast_path()
    got_x = np.concatenate(
        [np.asarray(bx._value) for bx, _ in dl])
    got_y = np.concatenate(
        [np.asarray(by._value) for _, by in dl])
    np.testing.assert_array_equal(got_x, x)
    np.testing.assert_array_equal(got_y, y)


def test_dataloader_tensor_dataset_python_path_still_works():
    x = paddle.to_tensor(np.arange(12, dtype="f4").reshape(6, 2))
    ds = TensorDataset([x])  # Tensor fields → python path
    dl = DataLoader(ds, batch_size=3, shuffle=False)
    assert not dl._use_native_fast_path()
    batches = list(dl)
    assert len(batches) == 2


def test_random_sampler_large_uses_native_and_is_reproducible():
    import paddle_tpu
    from paddle_tpu.io import RandomSampler, Dataset

    class Big(Dataset):
        def __len__(self):
            return 1 << 16

        def __getitem__(self, i):
            return i

    np.random.seed(7)
    a = list(RandomSampler(Big()))[:100]
    np.random.seed(7)
    b = list(RandomSampler(Big()))[:100]
    assert a == b and sorted(set(a)) != a  # shuffled, reproducible
