"""paddle_tpu.obs — runtime observability (ISSUE 5).

Three tiers: pure-host unit tests (histogram bucket math vs the
prometheus cumulative definition, stable-sorted snapshots, Chrome
trace-event schema round-trip), engine-integration tests (metrics
correctness under ragged arrivals with slot reuse and spec decode:
TTFT observed exactly once per request, the token counter matching the
emitted streams token-for-token), and the train-side wrapper
(step time / tokens-per-second into the same registry, analysis hooks
passing through untouched). The no-graph-change half of the story —
instrumented engines keeping byte-identical golden fingerprints — is
asserted where the fingerprints live (tests/test_serving.py budget
tests audit engines that now build with ``trace=True``, plus
``python -m paddle_tpu.obs check`` in scripts/check_graphs.sh)."""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.obs import (
    InstrumentedTrainStep, MetricsRegistry, ServingObs, TraceRecorder,
    load_chrome_trace, prometheus_from_snapshot, validate_chrome_trace,
)


# ------------------------------------------------------------ registry
def test_counter_and_gauge_basics():
    r = MetricsRegistry()
    c = r.counter("reqs_total", "requests")
    c.inc()
    c.inc(2.5)
    c.inc(1, route="spec")
    assert c.value() == 3.5
    assert c.value(route="spec") == 1.0
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = r.gauge("depth")
    g.set(7, pool="target")
    g.set(3, pool="draft")
    assert g.value(pool="target") == 7.0
    # same name, different kind -> loud failure
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("reqs_total")
    # create-or-get returns the same instrument
    assert r.counter("reqs_total") is c


def test_histogram_bucket_math_vs_reference():
    """Bucket placement vs the prometheus DEFINITION (le is <=,
    cumulative over buckets, +Inf overflow), computed independently
    with numpy over the raw observations."""
    buckets = (0.01, 0.1, 1.0, 5.0)
    r = MetricsRegistry()
    h = r.histogram("lat_seconds", buckets=buckets)
    rng = np.random.RandomState(0)
    values = np.concatenate([
        rng.exponential(0.5, 200),
        np.asarray(buckets),          # exact bounds land IN the bucket
        [7.5, 100.0],                 # +Inf overflow
    ])
    for v in values:
        h.observe(float(v))
    counts = h.bucket_counts()
    cum = np.cumsum(counts)
    for i, le in enumerate(buckets):
        assert cum[i] == int((values <= le).sum()), f"le={le}"
    assert cum[-1] == len(values)
    assert h.count() == len(values)
    assert h.sum() == pytest.approx(values.sum())
    q50 = h.quantile(0.5)
    assert 0 < q50 <= buckets[-1]
    # exposition: cumulative _bucket lines + +Inf + _sum/_count
    prom = r.prometheus()
    assert f'lat_seconds_bucket{{le="+Inf"}} {len(values)}' in prom
    assert "lat_seconds_count 206" in prom
    with pytest.raises(ValueError, match="increasing"):
        r2 = MetricsRegistry()
        r2.histogram("bad", buckets=(1.0, 1.0))


def test_snapshot_stable_sorted_and_prom_roundtrip():
    r = MetricsRegistry()
    # register in non-sorted order with label permutations
    r.gauge("zz").set(1, b="2", a="1")
    r.counter("aa").inc(3)
    r.histogram("mm", buckets=(1.0, 2.0)).observe(1.5)
    s1, s2 = r.snapshot_json(), r.snapshot_json()
    assert s1 == s2
    snap = json.loads(s1)
    assert [m["name"] for m in snap["metrics"]] == ["aa", "mm", "zz"]
    # offline re-render == live exposition (the CLI snapshot path)
    assert prometheus_from_snapshot(snap) == r.prometheus()
    assert 'zz{a="1",b="2"} 1' in r.prometheus()


# ------------------------------------------------------------ tracing
def test_trace_event_schema_roundtrip(tmp_path):
    t = TraceRecorder(epoch=100.0)
    t.thread_name(1, "slot0")
    t.complete("prefill", 100.001, 100.003, tid=1,
               args={"tokens": 4})
    t.instant("first_token", 100.0035, tid=1)
    t.counter("occupancy", 100.004, {"live": 2, "free": 1})
    path = str(tmp_path / "trace.json")
    t.save(path)
    obj = load_chrome_trace(path)  # validates on load
    evs = obj["traceEvents"]
    assert len(evs) == 4
    x = [e for e in evs if e["ph"] == "X"][0]
    assert x["ts"] == pytest.approx(1000.0)   # µs after epoch
    assert x["dur"] == pytest.approx(2000.0)
    assert x["args"]["tokens"] == 4
    assert obj["otherData"]["dropped_events"] == 0
    # schema violations are loud
    with pytest.raises(ValueError, match="missing 'traceEvents'"):
        validate_chrome_trace({"events": []})
    with pytest.raises(ValueError, match="missing 'dur'"):
        validate_chrome_trace({"traceEvents": [
            {"name": "x", "ph": "X", "pid": 1, "tid": 0, "ts": 0}]})


def test_trace_bounded_buffer_drops_not_grows():
    t = TraceRecorder(max_events=3, epoch=0.0)
    for i in range(10):
        t.instant(f"e{i}", 0.001 * i)
    assert len(t.events) == 3
    assert t.dropped == 7
    assert t.chrome_trace()["otherData"]["dropped_events"] == 7


# ---------------------------------------------- engine metrics (plain)
@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


def test_engine_metrics_ragged_slot_reuse(tiny_model):
    """5 ragged requests over 2 slots (retirement + slot reuse
    mid-run): TTFT observed exactly once per request, the emitted-token
    counter matches the streams token-for-token, latency histograms see
    every request, and the legacy stats view mirrors the registry."""
    from paddle_tpu.serving import ServingEngine

    cfg, model = tiny_model
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
               for n in (5, 9, 3, 7, 4)]
    max_new = [4, 3, 6, 2, 5]
    engine = ServingEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=4, decode_quantum=3,
                           trace=True)
    reqs = [engine.submit(p, max_new_tokens=mn)
            for p, mn in zip(prompts, max_new)]
    done = engine.run()
    assert len(done) == len(reqs)
    r = engine.obs.registry
    n_req = len(reqs)
    total_tokens = sum(len(q.tokens) for q in done)
    assert r.get("serving_requests_submitted_total").value() == n_req
    assert r.get("serving_requests_admitted_total").value() == n_req
    assert r.get("serving_requests_finished_total").value() == n_req
    # TTFT: once per request, never re-observed on slot reuse
    assert r.get("serving_ttft_seconds").count() == n_req
    assert r.get("serving_queue_wait_seconds").count() == n_req
    assert r.get("serving_e2e_latency_seconds").count() == n_req
    # token accounting matches the emitted streams exactly
    assert r.get("serving_tokens_emitted_total").value() == total_tokens
    assert engine.stats["generated_tokens"] == total_tokens
    # every request here emits >=2 tokens -> inter-token recorded
    assert r.get("serving_inter_token_seconds").count() == n_req
    # per-dispatch histogram saw mixed steps AND decode quanta
    hq = r.get("serving_quantum_seconds")
    assert hq.count(kind="mixed") == engine.stats["mixed_steps"]
    assert hq.count(kind="decode") == engine.stats["decode_quanta"]
    # legacy view IS the registry (one source of truth)
    assert (engine.stats["decode_quanta"]
            == r.get("serving_decode_quanta_total").value())
    # windowed throughput + pool gauges moved
    assert r.get("serving_tokens_per_second_window").value() > 0
    assert len(engine.obs.timeseries()["tokens_per_s"]) > 0
    assert r.get("serving_pool_utilization").value(pool="target") >= 0
    # trace: valid, with per-slot request spans and quantum spans
    obj = validate_chrome_trace(engine.obs.tracer.chrome_trace())
    names = [e["name"] for e in obj["traceEvents"]]
    assert sum(1 for n in names if n.startswith("req ")) == n_req
    assert "decode" in names and "mixed" in names
    # engine_stats keeps its historical dict shape
    st = engine.engine_stats()
    for key in ("steps", "mixed_steps", "decode_quanta", "pool",
                "admitted", "finished", "mean_occupancy"):
        assert key in st


def test_engine_metrics_spec_decode(tiny_model):
    """The speculative arm: same invariants (TTFT once, streams match)
    plus acceptance-rate instrumentation consistent with the legacy
    spec counters, and draft-pool gauges labeled separately. The
    flight recorder rides along (ISSUE 6) with a forced e2e trigger so
    every journal captures — its spec_round events must reconcile with
    the engine's acceptance counters."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.obs import FlightRecorder
    from paddle_tpu.serving import ServingEngine

    cfg, model = tiny_model
    paddle.seed(11)
    draft = LlamaForCausalLM(
        LlamaConfig.tiny(tensor_parallel=False, num_hidden_layers=1))
    draft.eval()
    engine = ServingEngine(model, spec_draft=draft, spec_gamma=2,
                           num_slots=2, block_size=4, prefill_chunk=3,
                           trace=True, slo=True,
                           flight=FlightRecorder(e2e_threshold=1e-9))
    rng = np.random.RandomState(5)
    reqs = [engine.submit(rng.randint(1, cfg.vocab_size, n)
                          .astype(np.int32), max_new_tokens=5)
            for n in (6, 4, 8)]
    done = engine.run()
    assert len(done) == len(reqs)
    r = engine.obs.registry
    total_tokens = sum(len(q.tokens) for q in done)
    assert r.get("serving_ttft_seconds").count() == len(reqs)
    assert r.get("serving_tokens_emitted_total").value() == total_tokens
    assert (r.get("serving_quantum_seconds").count(kind="spec_round")
            == engine.stats["spec_rounds"])
    assert (r.get("serving_spec_proposed_total").value()
            == engine.stats["spec_proposed"])
    rate = r.get("serving_spec_acceptance_rate").value()
    assert 0.0 <= rate <= 1.0
    assert len(engine.obs.timeseries()["spec_acceptance_rate"]) \
        == engine.stats["spec_rounds"]
    assert r.get("serving_pool_blocks_in_use").value(pool="draft") >= 0
    validate_chrome_trace(engine.obs.tracer.chrome_trace())
    # flight journals: every request captured (forced trigger), and
    # their spec_round events reconcile with the engine's counters
    recs = engine.flight.records()  # schema-validates
    assert len(recs) == len(reqs)
    spec_evs = [e for rec in recs for e in rec["events"]
                if e["kind"] == "spec_round"]
    assert spec_evs, "speculative rounds must be journaled"
    assert all(0 <= e["accepted"] <= e["proposed"] == 2
               for e in spec_evs)
    assert (sum(e["accepted"] for e in spec_evs)
            == engine.stats["spec_accepted"])
    # health evaluates over the same run (state depends on wall clock;
    # the report shape is the contract here)
    assert {o["name"] for o in engine.health()["objectives"]} \
        == {"ttft_p95", "inter_token_p99", "e2e_p99", "error_rate"}


def test_engine_obs_off_is_inert(tiny_model):
    """The overhead-bench baseline arm: rich hooks fully short-circuit
    (no histogram observations, no tracer), while the engine still
    runs and the legacy counters behind ``stats`` tick. One mixed step
    only — the decode quantum never compiles here."""
    from paddle_tpu.serving import ServingEngine

    cfg, model = tiny_model
    engine = ServingEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=8, decode_quantum=3,
                           obs="off")
    rng = np.random.RandomState(7)
    req = engine.submit(rng.randint(1, cfg.vocab_size, 5)
                        .astype(np.int32), max_new_tokens=4)
    engine.step()  # admit + full prefill -> first token emitted
    assert len(req.tokens) == 1
    r = engine.obs.registry
    assert r.get("serving_ttft_seconds").count() == 0
    assert r.get("serving_tokens_emitted_total").value() == 0
    assert r.get("serving_requests_submitted_total").value() == 0
    assert engine.obs.tracer is None
    assert engine.stats["steps"] == 1  # legacy counters still live
    assert engine.stats["mixed_steps"] == 1


# ------------------------------------------------------------ training
def test_instrumented_train_step():
    """Wrap a JittedTrainStep: step histogram/counters/gauges tick in
    the shared registry, report() summarizes, and the analysis hooks
    (lower/donatable_leaf_count) pass through to the SAME wrapped
    step."""
    from paddle_tpu.jit.train import JittedTrainStep

    paddle.seed(0)
    model = paddle.nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())

    def crit(out, label):
        d = out - label
        return (d * d).mean()

    step = JittedTrainStep(model, crit, opt)
    reg = MetricsRegistry()
    tracer = TraceRecorder()
    inst = InstrumentedTrainStep(step, registry=reg,
                                 tokens_per_step=16, tracer=tracer)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 8).astype("f4"))
    y = paddle.to_tensor(rng.randn(2, 8).astype("f4"))
    inst(x, y)
    l2 = inst(x, y)
    assert np.isfinite(float(np.asarray(l2._value)))
    assert reg.get("train_steps_total").value() == 2
    assert reg.get("train_step_seconds").count() == 2
    assert reg.get("train_tokens_total").value() == 32
    assert reg.get("train_tokens_per_second").value() > 0
    rep = inst.report()
    assert rep["n_steps_timed"] == 2 and rep["tokens_per_sec"] > 0
    # analysis hooks reach the wrapped step untouched
    assert inst.donatable_leaf_count() == step.donatable_leaf_count()
    assert inst.lower(x, y) is not None
    assert len(tracer.events) >= 2
    # serving + train can share one registry namespace-free
    assert "train_step_seconds" in reg.prometheus()


def test_for_transformer_flops_accounting():
    reg = MetricsRegistry()

    calls = []

    class FakeStep:
        def __call__(self, inputs, labels):
            calls.append(1)

            class L:
                _value = np.float32(0.5)

            return L()

    inst = InstrumentedTrainStep.for_transformer(
        FakeStep(), n_params=1000, tokens_per_step=64, registry=reg,
        sync=False)
    assert inst.model_flops_per_step == pytest.approx(6.0 * 1000 * 64)
    inst([], [])
    assert reg.get("train_model_tflops_per_second").value() > 0


# ------------------------------------------------------------ CLI
def test_obs_cli_offline_snapshot_and_trace(tmp_path, capsys):
    """The offline CLI paths (no engine, tier-1-cheap): `snapshot
    --in` re-renders a saved registry dump as prometheus text, and
    `export --in` validates a saved chrome trace."""
    from paddle_tpu.obs.__main__ import main

    reg = MetricsRegistry()
    reg.counter("serving_requests_finished_total").inc(4)
    reg.histogram("serving_ttft_seconds",
                  buckets=(0.01, 0.1)).observe(0.05)
    snap_path = str(tmp_path / "metrics.json")
    with open(snap_path, "w") as f:
        f.write(reg.snapshot_json())
    assert main(["snapshot", "--in", snap_path,
                 "--format", "prom"]) == 0
    out = capsys.readouterr().out
    assert "# TYPE serving_ttft_seconds histogram" in out
    assert "serving_requests_finished_total 4" in out
    t = TraceRecorder(epoch=0.0)
    t.complete("decode", 0.001, 0.002)
    trace_path = str(tmp_path / "trace.json")
    t.save(trace_path)
    assert main(["export", "--in", trace_path]) == 0
    # missing-input paths exit 2, not a stack trace
    assert main(["snapshot"]) == 2
    assert main(["export"]) == 2


@pytest.mark.slow
def test_obs_cli_demo_export_and_snapshot(tmp_path, capsys):
    """`python -m paddle_tpu.obs export --demo` end to end: drives a
    tiny engine and writes a Perfetto-valid trace + metrics snapshot
    (slow tier: one extra engine compile)."""
    from paddle_tpu.obs.__main__ import main

    trace_path = str(tmp_path / "trace.json")
    snap_path = str(tmp_path / "metrics.json")
    rc = main(["export", "--demo", "--out", trace_path,
               "--metrics-out", snap_path])
    assert rc == 0
    obj = load_chrome_trace(trace_path)
    assert len(obj["traceEvents"]) > 10
    rc = main(["snapshot", "--in", snap_path, "--format", "prom"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serving_requests_finished_total 4" in out
