"""Async checkpointing + preemption-aware elastic manager (SURVEY.md §5
failure detection / checkpoint-resume rows)."""
import os
import signal
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.checkpoint import (
    async_save_state_dict, CheckpointManager, load_state_dict,
)
from paddle_tpu.distributed.elastic import PreemptionGuard, ElasticManager


def _model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


def test_async_save_roundtrip(tmp_path):
    m = _model()
    path = str(tmp_path / "ckpt")
    handle = async_save_state_dict(m.state_dict(), path)
    handle.result()
    m2 = _model()
    for _, p in m2.named_parameters():
        p.set_value(paddle.to_tensor(np.zeros(p.shape, "f4")))
    load_state_dict(m2.state_dict(), path)
    for (k1, p1), (k2, p2) in zip(
        m.state_dict().items(), m2.state_dict().items()
    ):
        np.testing.assert_allclose(
            np.asarray(p1._value), np.asarray(p2._value), rtol=1e-6
        )


def test_async_save_snapshot_isolated_from_mutation(tmp_path):
    """Mutating params right after async_save must not corrupt the save."""
    m = _model()
    before = {k: np.asarray(v._value).copy()
              for k, v in m.state_dict().items()}
    path = str(tmp_path / "snap")
    handle = async_save_state_dict(m.state_dict(), path)
    for _, p in m.named_parameters():  # race: overwrite immediately
        p.set_value(paddle.to_tensor(np.full(p.shape, 7.0, "f4")))
    handle.result()
    m2 = _model()
    load_state_dict(m2.state_dict(), path)
    for k, v in m2.state_dict().items():
        np.testing.assert_allclose(np.asarray(v._value), before[k], rtol=1e-6)


def test_checkpoint_manager_retention_and_latest(tmp_path):
    m = _model()
    mgr = CheckpointManager(str(tmp_path / "root"), max_to_keep=2,
                            async_save=False)
    for step in (10, 20, 30):
        mgr.save(step, m.state_dict())
    assert mgr.latest_step() == 30
    assert sorted(mgr.all_steps()) == [20, 30]  # step_10 retired


def test_elastic_manager_resume_after_preemption(tmp_path):
    root = str(tmp_path / "elastic")
    m = _model()
    opt = paddle.optimizer.Adam(1e-2, parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype("f4"))
    y = paddle.to_tensor(np.random.RandomState(1).randn(8, 2).astype("f4"))
    mse = nn.MSELoss()

    def step_fn(step):
        loss = mse(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step == 4:  # simulate the platform preempting us mid-run
            os.kill(os.getpid(), signal.SIGTERM)

    em = ElasticManager(root, save_interval=100, async_save=False)
    start = em.resume(m.state_dict())
    assert start == 0
    last = em.run(lambda: m.state_dict(), step_fn, start, num_steps=100)
    assert last == 4  # stopped at the preempted step, checkpoint written
    assert em.manager.latest_step() == 4

    # "restart": fresh process state, resume from the checkpoint
    m2 = _model()
    em2 = ElasticManager(root, save_interval=100, async_save=False)
    start2 = em2.resume(m2.state_dict())
    assert start2 == 5
    for (_, p1), (_, p2) in zip(m.named_parameters(), m2.named_parameters()):
        np.testing.assert_allclose(
            np.asarray(p1._value), np.asarray(p2._value), rtol=1e-6
        )


def test_preemption_guard_restores_handler():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert not g.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        assert g.preempted
    assert signal.getsignal(signal.SIGTERM) is prev


def test_async_save_publishes_per_file_and_gcs_stale_shards(tmp_path):
    """Regression (round-2 advisor): the save publishes per-file (never
    swapping/deleting the shared directory, which on multi-process runs
    holds other live ranks' shards), while shards from a LARGER previous
    world — which no current rank overwrites — are GC'd so a stale
    later-sorted shard can't shadow fresh weights at load time."""
    import json

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    path = str(tmp_path / "ckpt")
    os.makedirs(path)
    stale = os.path.join(path, "shard_99.npz")
    np.savez(stale, other=np.ones(3))

    paddle.seed(0)
    m = nn.Linear(4, 4)
    handle = async_save_state_dict(m.state_dict(), path)
    handle.result()
    assert not os.path.exists(stale), "stale larger-world shard kept"
    assert os.path.exists(os.path.join(path, "shard_0.npz"))
    with open(os.path.join(path, "metadata.json")) as f:
        assert json.load(f)["__world_size__"]["value"] == 1
    # no stray tmp artifacts left behind
    assert not [f for f in os.listdir(path) if "tmp" in f]
    # roundtrip still resolves to the fresh weights
    m2 = nn.Linear(4, 4)
    load_state_dict(m2.state_dict(), path)
    np.testing.assert_array_equal(
        np.asarray(m2.weight._value), np.asarray(m.weight._value))
