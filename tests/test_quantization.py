"""Quantization subsystem: STE fake-quant, weight-only int8, a8w8 int32
accumulation, QAT/PTQ workflows (SURVEY.md §2.4 quantization row)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.quant import (
    fake_quantize_dequantize_abs_max, weight_quantize, weight_only_linear,
    a8w8_linear, quantize_linear, dequantize_linear, QuantizedLinear,
)
from paddle_tpu.quantization import (
    QuantConfig, QAT, PTQ, FakeQuanterWithAbsMax, QuantedLinear,
)


def test_fake_quant_values_and_ste_grad():
    x = paddle.to_tensor(np.linspace(-2, 2, 64).astype("f4"))
    x.stop_gradient = False
    q = fake_quantize_dequantize_abs_max(x)
    err = np.abs(np.asarray(q._value) - np.asarray(x._value)).max()
    assert err <= 2.0 / 127 + 1e-6  # one quantization step
    # STE: d/dx sum(q) == ones
    q.sum().backward()
    np.testing.assert_allclose(
        np.asarray(x.grad._value), np.ones(64, "f4"), rtol=1e-6
    )


def test_quantize_dequantize_roundtrip_per_channel():
    rng = np.random.RandomState(0)
    w_np = rng.randn(16, 8).astype("f4")
    w = paddle.to_tensor(w_np)
    scale = paddle.to_tensor(
        (np.abs(w_np).max(axis=0) / 127.0).astype("f4")
    )
    q = quantize_linear(w, scale, axis=1)
    assert str(q.dtype).endswith("int8")
    back = dequantize_linear(q, scale, axis=1)
    err = np.abs(np.asarray(back._value) - np.asarray(w._value)).max()
    assert err <= float(np.asarray(scale._value).max()) + 1e-6


def test_weight_only_linear_close_to_float():
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(4, 32).astype("f4"))
    w = paddle.to_tensor(rng.randn(32, 16).astype("f4"))
    b = paddle.to_tensor(rng.randn(16).astype("f4"))
    qw, scale = weight_quantize(w)
    y = weight_only_linear(x, qw, b, scale)
    ref = np.asarray((x @ w + b)._value)
    got = np.asarray(y._value)
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.02, rel


def test_a8w8_linear_int32_accumulation():
    rng = np.random.RandomState(2)
    x_np = rng.randn(4, 32).astype("f4")
    w_np = rng.randn(32, 16).astype("f4")
    xs = np.abs(x_np).max() / 127.0
    qx = paddle.to_tensor(
        np.clip(np.round(x_np / xs), -128, 127).astype("i1"))
    w = paddle.to_tensor(w_np)
    qw, wscale = weight_quantize(w)
    y = a8w8_linear(qx, qw, paddle.to_tensor(np.float32(xs)), wscale)
    ref = x_np @ w_np
    rel = np.abs(np.asarray(y._value) - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def _mlp():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def test_qat_quantize_train_convert():
    model = _mlp()
    cfg = QuantConfig(
        activation=FakeQuanterWithAbsMax(), weight=FakeQuanterWithAbsMax()
    )
    qat = QAT(cfg)
    qmodel = qat.quantize(model)
    assert isinstance(qmodel[0], QuantedLinear)

    opt = paddle.optimizer.Adam(1e-2, parameters=qmodel.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(32, 8).astype("f4"))
    y = paddle.to_tensor((np.abs(rng.randn(32)).astype("i8") % 4))
    ce = nn.CrossEntropyLoss()
    losses = []
    for _ in range(30):
        loss = ce(qmodel(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    infer = qat.convert(qmodel)
    assert isinstance(infer[0], QuantizedLinear)
    out = infer(x)
    assert out.shape == [32, 4]


def test_ptq_calibrate_convert_close_to_float():
    model = _mlp()
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(16, 8).astype("f4"))
    ref = np.asarray(model(x)._value)

    ptq = PTQ()
    qmodel = ptq.quantize(model)
    for _ in range(3):  # calibration passes
        qmodel(x)
    assert qmodel[0].observer.absmax > 0
    infer = ptq.convert(qmodel)
    got = np.asarray(infer(x)._value)
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-8)
    assert rel < 0.05, rel


def test_ptq_act_scale_feeds_a8w8():
    model = _mlp()
    rng = np.random.RandomState(4)
    x = paddle.to_tensor(rng.randn(16, 8).astype("f4"))
    ptq = PTQ()
    qmodel = ptq.quantize(model)
    qmodel(x)  # calibration
    infer = ptq.convert(qmodel)
    assert infer[0].act_scale is not None  # observers wired into convert
    ref = np.asarray(model(x)._value) if False else None
    out = infer(x)
    assert np.isfinite(np.asarray(out._value)).all()


def test_int8_llama_decode_parity_and_predictor(tmp_path):
    """Round-4 verdict #5: the serving stack consumes weight-only-int8
    artifacts end-to-end. (a) PTQ-converted Llama decodes with EXACT
    parity vs a float model holding the dequantized weights (wiring,
    not quant error); (b) the converted model survives jit.save →
    inference.Config → Predictor with the same outputs."""
    import os
    import jax.numpy as jnp
    import paddle_tpu.inference as infer
    from paddle_tpu.static import InputSpec
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import generate_on_device

    def build():
        paddle.seed(9)
        m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
        m.eval()
        return m

    q_model = PTQ(QuantConfig()).convert(PTQ(QuantConfig()).quantize(build()))
    # every Linear became weight-only int8
    qlinears = [l for _, l in q_model.named_sublayers()
                if isinstance(l, QuantizedLinear)]
    assert qlinears and all(
        l.quant_weight._value.dtype == jnp.int8 for l in qlinears)

    # float reference with the DEQUANTIZED weights installed
    ref = build()
    ref_linears = {n: l for n, l in ref.named_sublayers()
                   if isinstance(l, nn.Linear)}
    for name, ql in q_model.named_sublayers():
        if isinstance(ql, QuantizedLinear):
            w = (ql.quant_weight._value.astype(jnp.float32)
                 * ql.weight_scale._value[None, :])
            ref_linears[name].weight.set_value(paddle.Tensor(w))

    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(1, 128, (2, 8)))
    out_q = generate_on_device(q_model, ids, max_new_tokens=6)
    out_r = generate_on_device(ref, ids, max_new_tokens=6)
    np.testing.assert_array_equal(out_q.numpy(), out_r.numpy())

    # (b) Predictor path on the int8 artifact
    path = os.path.join(str(tmp_path), "llama_int8")
    paddle.jit.save(q_model, path, input_spec=[InputSpec([2, 8], "int64")])
    pred = infer.create_predictor(infer.Config(path))
    names = pred.get_input_names()
    pred.get_input_handle(names[0]).copy_from_cpu(ids.numpy())
    pred.run()
    got = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    want = q_model(ids).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


# -------------------------------------------- round-trip property tests
@pytest.mark.parametrize("algo", ["weight_only_int8", "llm.int8"])
def test_weight_quantize_roundtrip_bound_property(algo):
    """Per-out-channel round-trip bound, both algos: |w - deq(q(w))|
    <= scale/2 elementwise (round-to-nearest), across random draws
    spanning 6 decades of per-channel magnitude, with an all-zero
    out-channel (reconstructs exactly zero through the 1e-8 scale
    floor) and adjacent tiny/huge channels (one channel's dynamic
    range must never bleed into another's scale)."""
    from paddle_tpu.nn.quant import weight_dequantize

    rng = np.random.RandomState(3)
    for _ in range(5):
        w_np = (rng.randn(24, 12)
                * 10.0 ** rng.uniform(-3, 3, (1, 12))).astype("f4")
        w_np[:, 0] = 0.0
        w_np[:, 1] = rng.randn(24).astype("f4") * 1e-6
        w_np[:, 2] = rng.randn(24).astype("f4") * 1e6
        w = paddle.to_tensor(w_np)
        qw, scale = weight_quantize(w, algo=algo)
        assert str(qw.dtype).endswith("int8")
        s_np = np.asarray(scale._value)
        assert s_np.shape == (12,) and (s_np > 0).all()
        back = np.asarray(weight_dequantize(qw, scale, algo=algo)._value)
        bound = s_np[None, :] * (0.5 + 1e-5)
        assert (np.abs(back - w_np) <= bound).all()
        assert (back[:, 0] == 0.0).all()
        # per-channel scales: the huge channel's presence must not
        # coarsen the tiny channel below its own round-trip bound
        assert np.abs(back[:, 1] - w_np[:, 1]).max() <= s_np[1]


def test_quantize_kv_rows_roundtrip_and_row_locality():
    """The KV-row quantizer's two contracts: the per-row round-trip
    bound (|x - q*s| <= s/2 over the head_dim axis, zero rows exact),
    and ROW LOCALITY — a row's (q, scale) depends only on that row's
    own values, the invariant that makes int8 pool content independent
    of chunk/quantum decomposition and keeps COW sharers bit-stable."""
    import jax.numpy as jnp

    from paddle_tpu.nn.quant import quantize_kv_rows

    rng = np.random.RandomState(4)
    x = (rng.randn(3, 4, 2, 16)
         * 10.0 ** rng.uniform(-4, 4, (3, 4, 2, 1))).astype("f4")
    x[0, 0] = 0.0
    q, s = quantize_kv_rows(jnp.asarray(x))
    assert q.dtype == jnp.int8 and q.shape == x.shape
    s_np = np.asarray(s)
    assert s_np.shape == x.shape[:-1] and (s_np > 0).all()
    back = np.asarray(q, dtype=np.float32) * s_np[..., None]
    assert (np.abs(back - x) <= s_np[..., None] * (0.5 + 1e-5)).all()
    assert (back[0, 0] == 0.0).all()
    # row locality: quantizing any sub-slab reproduces the same rows
    q2, s2 = quantize_kv_rows(jnp.asarray(x[1:2]))
    np.testing.assert_array_equal(np.asarray(q[1:2]), np.asarray(q2))
    np.testing.assert_array_equal(s_np[1:2], np.asarray(s2))
