"""Quantized serving end-to-end (ISSUE 14): int8 weights + int8 KV
through the quantum family, the spec round, and prefix/COW sharing.

Engine level: the weight-only int8 engine is BIT-EXACT against a float
engine holding the dequantized weights (the dequant-into-the-matmul
multiply is IEEE-exact per element, so the oracle is equality, not
tolerance); the fixed-seed sampling arm and the speculative round with
draft == target both replay the plain int8 sampling engine bit-for-bit;
greedy streams are invariant to how a sequence is decomposed into
prefill chunks / decode quanta (the per-row KV scale depends only on
the row's own values); and a prefix-shared int8 engine stays
bit-identical to the unshared one through a real hit + COW.

Pool level: COW on an int8 pool copies the scale rows with the block
(the writer's divergence never moves a sharer's dequantized values),
LRU eviction reclaims scale rows with their blocks, dtype-aware byte
accounting tracks actual itemsize + scale bytes, and a 100-round
seeded ragged churn leaks nothing on target- and draft-shaped int8
pools.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp import PagedKVCachePool
from paddle_tpu.nn.layer.common import Linear
from paddle_tpu.nn.quant import quantize_kv_rows, weight_quantize
from paddle_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def tiny_cfg():
    return LlamaConfig.tiny(tensor_parallel=False)


def _fresh_model(cfg):
    """Each quantized engine needs its OWN model: the quantize sweep
    rewrites the Linear layers in place. Same seed -> same weights."""
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model


def _dequantize_weights_in_place(model):
    """The parity oracle's reference: every Linear weight replaced by
    ``dequant(quant(w))`` — the exact float matrix the int8 engine's
    fused dequant feeds its matmuls."""
    def walk(layer):
        for sub in layer._sub_layers.values():
            if isinstance(sub, Linear):
                qw, ws = weight_quantize(sub.weight)
                deq = (np.asarray(qw._value).astype(np.float32)
                       * np.asarray(ws._value)[None, :])
                sub.weight.set_value(paddle.to_tensor(deq))
            else:
                walk(sub)

    walk(model)
    return model


def _run(model, prompts, max_new, seeds=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("decode_quantum", 3)
    eng = ServingEngine(model, **kw)
    reqs = [eng.submit(p, max_new_tokens=mn, req_id=f"r{i}",
                       seed=seeds[i] if seeds else 0)
            for i, (p, mn) in enumerate(zip(prompts, max_new))]
    eng.run()
    return eng, [list(r.tokens) for r in reqs]


def _prompts(cfg, seed=0, lens=(5, 9)):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
            for n in lens]


# ------------------------------------------------ engine parity oracles
def test_weight_only_engine_bit_exact_vs_dequant_float(tiny_cfg):
    """weight_only_linear computes ``x @ (wq.astype(f32) * ws)`` — per
    element IEEE-exact dequant, so the int8-weight engine must equal a
    float engine holding those dequantized matrices BIT-FOR-BIT, not
    within tolerance. The same run pins the int8-KV arm against it:
    same weights + int8 pool must still produce the identical greedy
    streams on this fixture (per-row scales keep the tiny-logit
    argmaxes stable)."""
    prompts = _prompts(tiny_cfg)
    max_new = [6, 5]
    ref = _dequantize_weights_in_place(_fresh_model(tiny_cfg))
    _, want = _run(ref, prompts, max_new)
    q_eng, got = _run(_fresh_model(tiny_cfg), prompts, max_new,
                      quantize="weight_only_int8")
    assert got == want
    assert not q_eng.pool.quantized  # weights-only: pool stays float
    kv_eng, got_kv = _run(_fresh_model(tiny_cfg), prompts, max_new,
                          quantize="weight_only_int8", kv_dtype="int8")
    assert got_kv == want
    assert kv_eng.pool.quantized
    # dtype-aware accounting: the int8 pool pins well under half the
    # float pool's bytes for the same allocated blocks
    st_f, st_q = (e.pool.fragmentation_stats() for e in (q_eng, kv_eng))
    assert st_q["kv_dtype"] == "int8" and st_f["kv_dtype"] != "int8"
    per_f = st_f["bytes_in_use"] / max(st_f["blocks_in_use"], 1)
    per_q = st_q["bytes_in_use"] / max(st_q["blocks_in_use"], 1)
    assert 0 < per_q < 0.5 * per_f


@pytest.mark.slow
def test_int8_sampling_and_spec_round_parity_fixed_seeds(tiny_cfg):
    """The sampling arm on a fully quantized engine is deterministic
    on fixed seeds, and the speculative round with draft == target
    (both swept int8, BOTH pools int8 with their own scale pools)
    replays it bit-for-bit — q == p so every proposal accepts, and the
    fold_in(key, n_emitted) stream discipline carries over unchanged
    because quantization touches storage, not the token-draw path."""
    prompts = _prompts(tiny_cfg, seed=2, lens=(5, 7))
    max_new = [5, 5]
    kw = dict(quantize="weight_only_int8", kv_dtype="int8",
              decode_strategy="sampling", top_k=8, temperature=0.9)
    _, want = _run(_fresh_model(tiny_cfg), prompts, max_new,
                   seeds=[0, 1], **kw)
    model = _fresh_model(tiny_cfg)
    spec, got = _run(model, prompts, max_new, seeds=[0, 1],
                     spec_draft=model, spec_gamma=2, **kw)
    assert got == want
    assert spec.pool.quantized and spec.d_pool.quantized
    st = spec.engine_stats()
    assert st["spec_proposed"] > 0
    assert st["spec_accepted"] == st["spec_proposed"]  # q == p


@pytest.mark.slow
def test_int8_greedy_invariant_to_chunk_quantum_decomposition(tiny_cfg):
    """A KV row's scale depends only on that row's own values, so the
    quantized pool content — and every downstream logit — is identical
    no matter how the sequence is cut into prefill chunks and decode
    quanta."""
    prompts = _prompts(tiny_cfg, seed=4, lens=(6, 10))
    max_new = [6, 5]
    kw = dict(quantize="weight_only_int8", kv_dtype="int8")
    _, a = _run(_fresh_model(tiny_cfg), prompts, max_new,
                prefill_chunk=4, decode_quantum=3, **kw)
    _, b = _run(_fresh_model(tiny_cfg), prompts, max_new,
                prefill_chunk=8, decode_quantum=2, **kw)
    assert a == b


@pytest.mark.slow
def test_int8_prefix_shared_streams_bit_identical(tiny_cfg):
    """Sharing composes with quantization: an int8 engine with the
    prefix cache on — through a real hit AND a real COW (the bare
    system prompt's capped re-prefill) — matches the unshared int8
    engine bit-for-bit."""
    rng = np.random.RandomState(3)
    sys_p = rng.randint(1, tiny_cfg.vocab_size, 8).astype(np.int32)
    tail = rng.randint(1, tiny_cfg.vocab_size, 3).astype(np.int32)
    prompts = [np.concatenate([sys_p, tail]), sys_p.copy()]
    max_new = [5, 4]
    kw = dict(quantize="weight_only_int8", kv_dtype="int8")

    def run_seq(model, **extra):
        # sequential submits: the follower only sees a published prefix
        # if the leader finished first — that ordering IS the hit
        eng = ServingEngine(model, num_slots=2, block_size=4,
                            prefill_chunk=4, decode_quantum=3,
                            **kw, **extra)
        outs = []
        for i, (p, mn) in enumerate(zip(prompts, max_new)):
            r = eng.submit(p, max_new_tokens=mn, req_id=f"r{i}", seed=0)
            eng.run()
            outs.append(list(r.tokens))
        return eng, outs

    _, want = run_seq(_fresh_model(tiny_cfg))
    shared, got = run_seq(_fresh_model(tiny_cfg), prefix_cache=True)
    assert got == want
    assert shared.pool.prefix_hits >= 2
    assert shared.pool.cow_copies >= 1


# ------------------------------------------------ int8 pool mechanics
def _i8pool(num_blocks=8, bs=4, hk=2, d=8, prefix=True):
    return PagedKVCachePool(num_blocks=num_blocks, block_size=bs,
                            num_kv_heads=hk, head_dim=d,
                            dtype=jnp.float32, kv_dtype="int8",
                            prefix_cache=prefix)


def _audit(pool):
    """Refcount-granularity leak oracle (same as test_prefix_cache's),
    plus the int8 pool's dtype-aware byte accounting: bytes_in_use
    must be exactly blocks_in_use x the per-block cost of int8 rows +
    f32 scale rows."""
    expect = {}
    for table in pool._tables.values():
        for b in table:
            expect[b] = expect.get(b, 0) + 1
    for b in pool._cached_blocks:
        expect[b] = expect.get(b, 0) + 1
    assert expect == pool._refcounts
    assert len(pool._free) + len(expect) == pool.num_blocks
    st = pool.fragmentation_stats()
    assert 0.0 <= st["utilization"] <= 1.0
    assert st["blocks_in_use"] == len(expect)
    assert st["kv_dtype"] == "int8"
    rows = pool.block_size * pool.num_kv_heads
    per_block = 2 * pool.num_layers * rows * (pool.head_dim * 1 + 4)
    assert st["bytes_in_use"] == len(expect) * per_block
    assert pool.bytes_in_use() == st["bytes_in_use"]


def _fill_block(pool, blk, content):
    """Write REAL quantized rows + their scales into one block."""
    q, s = quantize_kv_rows(jnp.asarray(content))
    pool.k_pools[0] = pool.k_pools[0].at[blk].set(q)
    pool.k_scales[0] = pool.k_scales[0].at[blk].set(s)


def _dequant_block(pool, blk):
    return (np.asarray(pool.k_pools[0][blk], np.float32)
            * np.asarray(pool.k_scales[0][blk])[..., None])


def test_cow_copies_scale_rows_sharer_dequant_bit_stable():
    """First write into a shared int8 block lands in a fresh copy THAT
    CARRIES THE SCALE ROWS; the writer then diverging (new content AND
    new scales) must not move a single bit of the sharer's dequantized
    values."""
    pool = _i8pool()
    toks = np.arange(8, dtype=np.int32)
    rng = np.random.RandomState(5)
    content = rng.randn(2, 4, 2, 8).astype(np.float32) * 3.0
    pool.ensure("a", 8)
    for i, blk in enumerate(pool._tables["a"]):
        _fill_block(pool, blk, content[i])
    pool.publish_prefix("a", toks)
    assert pool.attach_prefix("b", toks) == 8
    shared = list(pool._tables["b"])
    before = [_dequant_block(pool, b) for b in shared]
    assert pool.make_writable("b", 4, 8) == 1  # tail block only
    fresh = pool._tables["b"][1]
    assert fresh != shared[1]
    np.testing.assert_array_equal(
        np.asarray(pool.k_scales[0][fresh]),
        np.asarray(pool.k_scales[0][shared[1]]))
    # the writer diverges in BOTH the int8 rows and the scale rows
    _fill_block(pool, fresh, content[1] * 7.0)
    for blk, want in zip(shared, before):
        np.testing.assert_array_equal(_dequant_block(pool, blk), want)
    assert pool.cow_copies == 1
    _audit(pool)


def test_int8_pool_lru_eviction_reclaims_scale_rows():
    """Eviction on the quantized pool: refcount-respecting, LRU
    leaf-first — and every reclaimed block returns its scale bytes to
    the dtype-aware accounting."""
    pool = _i8pool()
    old = np.arange(8, dtype=np.int32)
    new = np.arange(100, 108, dtype=np.int32)
    pool.ensure("a", 8)
    pool.publish_prefix("a", old)
    pool.ensure("b", 8)
    pool.publish_prefix("b", new)
    assert pool.evict_prefix(8) == 0  # live holders pin everything
    bytes_live = pool.bytes_in_use()
    pool.free("a")
    pool.free("b")
    assert pool.bytes_in_use() == bytes_live  # cache still holds all 4
    assert pool.evict_prefix(1) == 1          # old chain's leaf first
    assert pool.match_prefix(old) == 4
    assert pool.match_prefix(new) == 8
    rows = pool.block_size * pool.num_kv_heads
    per_block = 2 * pool.num_layers * rows * (pool.head_dim + 4)
    assert pool.bytes_in_use() == bytes_live - per_block
    _audit(pool)


@pytest.mark.parametrize(
    "geom", [(16, 4, 2, 8), (12, 4, 1, 4)], ids=["target", "draft"])
def test_int8_pool_ragged_churn_100_rounds_zero_leaks(geom):
    """100 seeded rounds of ragged admit/attach/publish/COW/trim/free/
    evict on an int8 pool — target- and draft-shaped — with the
    refcount + byte-accounting audit after EVERY round."""
    nb, bs, hk, d = geom
    rng = np.random.RandomState(2)  # this seed hits COW on both geoms
    pool = PagedKVCachePool(num_blocks=nb, block_size=bs,
                            num_kv_heads=hk, head_dim=d,
                            dtype=jnp.float32, kv_dtype="int8",
                            prefix_cache=True)
    live, counter = {}, 0
    for _ in range(100):
        op = rng.rand()
        if op < 0.55 and len(live) < 6:
            sid = f"s{counter}"
            counter += 1
            toks = rng.randint(0, 3,
                               rng.randint(1, 21)).astype(np.int32)
            try:
                matched = pool.attach_prefix(sid, toks)
                pool.ensure(sid, len(toks))
                if rng.rand() < 0.25:
                    pool.make_writable(sid, 0, len(toks))
                else:
                    pool.make_writable(sid, matched, len(toks))
                pool.publish_prefix(sid, toks)
                live[sid] = toks
            except RuntimeError:
                pool.free(sid)  # exhausted mid-growth: roll back
                if live:
                    victim = list(live)[rng.randint(len(live))]
                    live.pop(victim)
                    pool.free(victim)
        elif op < 0.75 and live:
            victim = list(live)[rng.randint(len(live))]
            live.pop(victim)
            pool.free(victim)
        elif op < 0.85 and live:
            sid = list(live)[rng.randint(len(live))]
            keep = rng.randint(0, len(live[sid]) + 1)
            pool.trim(sid, keep)
        else:
            pool.evict_prefix(rng.randint(0, 3))
        _audit(pool)
    assert pool.prefix_hits > 0 and pool.cow_copies > 0
    for sid in list(live):
        pool.free(sid)
    pool.clear_prefix_cache()
    assert pool.free_blocks == pool.num_blocks
    assert pool.bytes_in_use() == 0
