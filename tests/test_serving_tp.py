"""TP-sharded serving (ISSUE 11): the whole quantum family on the mesh.

Tier-1 keeps the cheap-but-sharp end: the tp2 greedy engine with the
prefix cache ON must stream BIT-EXACT against the per-request
sequential oracle (the same single-chip reference test_serving pins the
tp=1 engine to), including full-prompt prefix hits and a COW re-prefill
— one engine build covers the greedy, prefix-hit and COW arms at once.
The same run asserts the build-time collective census (gauges +
``engine_stats()`` + dashboard line) and the per-chip pool residency
split. Around it: the mesh-aware paged-pool adversarial suite (sharded
COW, preempt/resume aliasing, and the refcount-granularity ragged
churn from tests/test_prefix_cache.py re-run on a tp2 pool layout —
pure host allocator work, no compiles) and the mesh-kwarg error paths
(all raise before any tracing).

The expensive engine-vs-engine parities (fixed-seed sampling with
``per_request_sampling``, the speculative draft+verify round) are
``slow``: each builds two engines. Run them with ``-m slow``.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp import PagedKVCachePool
from paddle_tpu.nlp.generation import generate_on_device
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.engine import _resolve_tp_mesh


def _mesh(n=2):
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), ("mp",))


@pytest.fixture(scope="module")
def tp_model():
    """A tensor-parallel tiny llama built WITHOUT a mesh: mp layers
    degrade to their serial twins at init, so the same seed gives the
    single-chip reference and the tp2 engine identical weights — the
    bit-exactness oracle's foundation."""
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=True)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


def _oracle_row(model, prompt, max_new):
    out = generate_on_device(model, paddle.to_tensor(prompt[None, :]),
                             max_new_tokens=max_new)
    return np.asarray(out._value)[0]


# ------------------------------------------------ tp2 parity (tier-1)
def test_tp2_greedy_prefix_stream_parity(tp_model):
    """The headline oracle: a tp=2 engine with ``prefix_cache=True``
    streams bit-exact vs sequential single-chip generation — 5
    requests where two share an 8-token (2-block) prefix and one is an
    exact resubmit, so the run exercises a full-prompt prefix hit AND
    the COW copy its capped re-prefill forces, all through the SHARDED
    pool. The same build carries the obs satellite: the collective
    census lands in the gauges, ``engine_stats()`` and the dashboard,
    and pool residency reports per-chip bytes."""
    cfg, model = tp_model
    rng = np.random.RandomState(0)
    ragged = [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
              for n in (5, 9)]
    shared = rng.randint(1, cfg.vocab_size, 8).astype(np.int32)
    sp = [np.concatenate(
        [shared, rng.randint(1, cfg.vocab_size, 3).astype(np.int32)])
        for _ in range(2)]
    # wave 1 publishes ``shared``'s two full blocks; wave 2 re-submits
    # the exact 8-token prompt (full-prompt hit whose capped one-token
    # re-prefill COWs the shared tail block) plus a second extension
    # (2-block prefix hit) — all against the sequential oracle
    wave1, wave2 = ragged + [sp[0], shared], [shared, sp[1]]
    max_new = {id(p): mn for p, mn in
               zip(wave1 + wave2, (6, 4, 5, 5, 5, 5))}
    wants = {id(p): _oracle_row(model, p, max_new[id(p)])
             for p in wave1 + wave2}

    engine = ServingEngine(model, num_slots=3, block_size=4,
                           prefill_chunk=4, decode_quantum=3,
                           prefix_cache=True, tp=2)
    reqs = []
    for wave in (wave1, wave2):
        batch = [(engine.submit(p, max_new_tokens=max_new[id(p)]), p)
                 for p in wave]
        engine.run()
        reqs += batch
    for req, p in reqs:
        np.testing.assert_array_equal(engine.output_tokens(req),
                                      wants[id(p)])
    # the sharded pool really took the prefix-cache fast paths
    assert engine.pool.prefix_hits >= 2
    assert engine.pool.cow_copies >= 1
    assert engine.pool.tp_shards == 2
    assert engine.pool.per_chip_bytes_in_use() * 2 == \
        engine.pool.bytes_in_use()

    # obs satellite: census from the COMPILED quantum at build time
    qc = engine.quantum_collectives
    assert qc["tp"] == 2 and qc["count_total"] > 0
    assert qc["bytes_total"] > 0
    assert "all-reduce" in qc["by_kind"]
    st = engine.engine_stats()
    assert st["tp"] == 2
    assert st["quantum_collectives"]["bytes_total"] == qc["bytes_total"]
    assert st["pool_bytes_per_chip"] == engine.pool.per_chip_bytes_in_use()
    reg = engine.obs.registry
    assert reg.get("serving_collective_bytes_total").value() == \
        qc["bytes_total"]
    assert reg.get("serving_collective_count_total").value(
        kind="all-reduce") == qc["by_kind"]["all-reduce"]["count"]
    from paddle_tpu.obs.export import render_dashboard
    dash = render_dashboard(reg.snapshot())
    assert "collectives/quantum" in dash


# ----------------------------------------------- slow engine parities
@pytest.mark.slow
def test_tp2_per_request_sampling_parity(tp_model):
    """Fixed-seed sampling through the front-door quantum variant:
    per-slot temperatures + per-request seeds, tp1 vs tp2 engines on
    the SAME weights — streams must match bit-for-bit (the collectives
    change where the math runs, not what it computes)."""
    cfg, model = tp_model
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
               for n in (5, 9, 3)]

    def run(tp):
        eng = ServingEngine(model, num_slots=3, block_size=4,
                            prefill_chunk=4, decode_quantum=3,
                            decode_strategy="sampling", temperature=0.8,
                            per_request_sampling=True,
                            **({"tp": tp} if tp else {}))
        reqs = [eng.submit(p, max_new_tokens=5, seed=i,
                           temperature=0.7 if i % 2 else 1.2)
                for i, p in enumerate(prompts)]
        eng.run()
        return [eng.output_tokens(r) for r in reqs]

    for a, b in zip(run(0), run(2)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_tp2_spec_round_parity(tp_model):
    """The speculative draft+verify round under tp2: BOTH models shard
    onto the same mesh, both paged pools split along kv heads, the
    round stays one dispatch — and greedy spec output is bit-exact vs
    the tp1 spec engine (which is itself exact by construction)."""
    cfg, model = tp_model
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
               for n in (5, 7)]

    def draft():
        paddle.seed(11)
        d = LlamaForCausalLM(LlamaConfig.tiny(
            tensor_parallel=True, num_hidden_layers=1))
        d.eval()
        return d

    def run(tp):
        eng = ServingEngine(model, num_slots=2, block_size=4,
                            prefill_chunk=4, spec_draft=draft(),
                            spec_gamma=3, **({"tp": tp} if tp else {}))
        reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
        eng.run()
        assert eng.engine_stats()["spec_rounds"] > 0
        return eng, [eng.output_tokens(r) for r in reqs]

    _, o1 = run(0)
    e2, o2 = run(2)
    for a, b in zip(o1, o2):
        np.testing.assert_array_equal(a, b)
    assert e2.quantum_collectives["count_total"] > 0
    assert e2.d_pool.tp_shards == 2


# -------------------------------------- mesh-aware paged pool (host)
def _mesh_pool(num_blocks=16, bs=4, kv_heads=2):
    return PagedKVCachePool(num_blocks=num_blocks, block_size=bs,
                            num_kv_heads=kv_heads, head_dim=8,
                            dtype=jnp.float32, prefix_cache=True,
                            mesh=_mesh(2))


def _audit(pool):
    """Refcount-granularity leak oracle — the same invariant walk as
    tests/test_prefix_cache.py::_audit, re-run here against the
    SHARDED pool: every block's refcount equals its holder count, free
    list and held set partition the pool, stats stay sane."""
    expect = {}
    for table in pool._tables.values():
        for b in table:
            expect[b] = expect.get(b, 0) + 1
    for b in pool._cached_blocks:
        expect[b] = expect.get(b, 0) + 1
    assert expect == pool._refcounts
    assert len(pool._free) + len(expect) == pool.num_blocks
    assert not (set(pool._free) & set(expect))
    st = pool.fragmentation_stats()
    assert 0.0 <= st["utilization"] <= 1.0
    assert st["blocks_in_use"] == len(expect)


def _assert_sharded(pool):
    """Every layer's K/V pool array still carries the kv-head split —
    COW writes and publishes must never silently decay to replicated."""
    from jax.sharding import PartitionSpec

    want = PartitionSpec(None, None, "mp", None)
    for arr in pool.k_pools + pool.v_pools:
        assert arr.sharding.spec == want, arr.sharding


def test_mesh_pool_layout_and_fallback():
    """kv_heads divisible by mp -> pools split along the head axis and
    residency reports per-chip bytes; a non-divisible head count falls
    back to replicated (tp_shards == 1) instead of failing."""
    pool = _mesh_pool()
    assert pool.tp_shards == 2
    _assert_sharded(pool)
    pool.ensure("a", 8)
    assert pool.per_chip_bytes_in_use() * 2 == pool.bytes_in_use()
    odd = PagedKVCachePool(num_blocks=4, block_size=4, num_kv_heads=3,
                           head_dim=8, dtype=jnp.float32,
                           mesh=_mesh(2))
    assert odd.tp_shards == 1
    assert odd.per_chip_bytes_in_use() == odd.bytes_in_use()


def test_mesh_pool_cow_keeps_rows_and_sharding():
    """COW under the tp2 layout: the writer moves to a fresh block,
    the survivor keeps the original device rows, refcounts rebalance —
    and every pool array KEEPS its NamedSharding through the
    ``.at[].set`` copy (the _pin re-commit)."""
    pool = _mesh_pool()
    toks = np.array([1, 2, 3, 4, 5, 6, 7, 8], dtype=np.int32)
    assert pool.attach_prefix("a", toks) == 0
    pool.ensure("a", 8)
    pool.make_writable("a", 0, 8)
    marker = jnp.full((2, 8), 7.0)
    blk = pool._tables["a"][0]
    pool.k_pools[0] = pool._pin(
        pool.k_pools[0].at[blk, 0].set(marker))
    pool.publish_prefix("a", toks)
    assert pool.attach_prefix("b", toks) == 8
    pool.ensure("b", 8)
    shared = list(pool._tables["b"])
    pool.make_writable("b", 4, 8)  # COW the tail block only
    assert pool._tables["b"][0] == shared[0]
    assert pool._tables["b"][1] != shared[1]
    assert pool.cow_copies >= 1
    _assert_sharded(pool)
    # the survivor's rows are untouched by b's copy
    np.testing.assert_array_equal(
        np.asarray(pool.k_pools[0][pool._tables["a"][0], 0]),
        np.asarray(marker))
    _audit(pool)


def test_mesh_pool_preempt_resume_aliasing():
    """Preempt/resume under tp2: freeing a sharer mid-run releases only
    its holds (the index + survivor keep the blocks), and the resumed
    sequence re-attaches through the prefix index — the aliasing
    bookkeeping is layout-independent, and the audit proves it."""
    pool = _mesh_pool()
    toks = np.arange(1, 9, dtype=np.int32)
    pool.attach_prefix("a", toks)
    pool.ensure("a", 8)
    pool.make_writable("a", 0, 8)
    pool.publish_prefix("a", toks)
    assert pool.attach_prefix("b", toks) == 8
    pool.ensure("b", 8)
    _audit(pool)
    pool.free("b")  # preemption: drop the sharer's holds
    _audit(pool)
    hits = pool.prefix_hits
    assert pool.attach_prefix("b", toks) == 8  # resume re-aliases
    pool.ensure("b", 8)
    assert pool.prefix_hits > hits
    _audit(pool)
    _assert_sharded(pool)
    pool.free("a")
    pool.free("b")
    pool.clear_prefix_cache()
    assert pool.free_blocks == pool.num_blocks


def test_mesh_pool_ragged_churn_zero_leaks():
    """The 100-round seeded ragged churn from test_prefix_cache re-run
    on the SHARDED pool: admit/attach/publish/COW/trim/free/evict with
    the refcount audit after every round, plus the sharding invariant —
    teardown returns the pool to pristine."""
    rng = np.random.RandomState(42)
    pool = _mesh_pool(num_blocks=16, bs=4)
    live, counter = {}, 0
    for _ in range(100):
        op = rng.rand()
        if op < 0.55 and len(live) < 6:
            sid = f"s{counter}"
            counter += 1
            toks = rng.randint(0, 3,
                               rng.randint(1, 21)).astype(np.int32)
            try:
                matched = pool.attach_prefix(sid, toks)
                pool.ensure(sid, len(toks))
                if rng.rand() < 0.25:
                    pool.make_writable(sid, 0, len(toks))
                else:
                    pool.make_writable(sid, matched, len(toks))
                pool.publish_prefix(sid, toks)
                live[sid] = toks
            except RuntimeError:
                pool.free(sid)
                if live:
                    victim = list(live)[rng.randint(len(live))]
                    live.pop(victim)
                    pool.free(victim)
        elif op < 0.75 and live:
            victim = list(live)[rng.randint(len(live))]
            live.pop(victim)
            pool.free(victim)
        elif op < 0.85 and live:
            sid = list(live)[rng.randint(len(live))]
            keep = rng.randint(0, len(live[sid]) + 1)
            pool.trim(sid, keep)
        else:
            pool.evict_prefix(rng.randint(0, 3))
        _audit(pool)
    assert pool.prefix_hits > 0 and pool.cow_copies > 0
    _assert_sharded(pool)
    for sid in list(live):
        pool.free(sid)
    pool.clear_prefix_cache()
    assert pool.free_blocks == pool.num_blocks
    assert not pool._refcounts and not pool._tables


# --------------------------------------- mesh kwarg error paths (host)
def test_tp_too_many_devices_is_actionable():
    with pytest.raises(ValueError,
                       match="xla_force_host_platform_device_count"):
        _resolve_tp_mesh(None, 64)


def test_tp_mesh_needs_mp_axis():
    from jax.sharding import Mesh

    data = Mesh(np.array(jax.devices()[:2]), ("data",))
    with pytest.raises(ValueError, match="no 'mp' axis"):
        _resolve_tp_mesh(data, None)


def test_tp_mesh_tp_disagreement():
    with pytest.raises(ValueError, match="disagrees"):
        _resolve_tp_mesh(_mesh(2), 4)


def test_tp_mesh_size_one_is_single_chip():
    mesh, tp = _resolve_tp_mesh(_mesh(1), None)
    assert mesh is None and tp == 1
    mesh, tp = _resolve_tp_mesh(None, 2)
    assert tp == 2 and mesh.shape["mp"] == 2


def test_tp_head_divisibility_checked_before_tracing(tp_model):
    cfg, model = tp_model
    with pytest.raises(ValueError, match="must divide by tp=8"):
        ServingEngine(model, num_slots=2, block_size=4, tp=8)


def test_tp_rejects_serial_model_before_tracing():
    paddle.seed(3)
    serial = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    serial.eval()
    with pytest.raises(ValueError, match="tensor_parallel=True"):
        ServingEngine(serial, num_slots=2, block_size=4, tp=2)
