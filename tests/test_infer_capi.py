"""C client inference API (reference: the pd_inference_api.h C surface,
SURVEY.md §2.6 — unverified): build the embedding shim with g++, compile
a REAL C client against it, and check its output against the Python
predictor. Skips cleanly when the embedding toolchain is unavailable."""
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import paddle_tpu as paddle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSRC = os.path.join(REPO, "csrc")

C_CLIENT = r"""
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include "paddle_tpu_infer_capi.h"

struct CloneJob {
  PD_Predictor* pred;
  long long total;
  float* buf;
  int rc;
};

static void* run_clone(void* arg) {
  struct CloneJob* job = (struct CloneJob*)arg;
  int64_t shape[2] = {2, 8};
  float ones[16];
  for (int i = 0; i < 16; ++i) ones[i] = 1.0f;
  PD_Tensor* cin = PD_PredictorGetInputHandle(
      job->pred, PD_PredictorGetInputName(job->pred, 0));
  PD_TensorReshape(cin, 2, shape);
  PD_TensorCopyFromCpuFloat(cin, ones);
  if (PD_PredictorRun(job->pred) != 0) { job->rc = 1; return NULL; }
  PD_Tensor* cout = PD_PredictorGetOutputHandle(
      job->pred, PD_PredictorGetOutputName(job->pred, 0));
  PD_TensorCopyToCpuFloat(cout, job->buf);
  job->rc = 0;
  return NULL;
}

int main(int argc, char** argv) {
  PD_Config* cfg = PD_ConfigCreate();
  PD_ConfigSetModel(cfg, argv[1], NULL);
  PD_Predictor* pred = PD_PredictorCreate(cfg);
  if (!pred) { fprintf(stderr, "create: %s\n", PD_GetLastError()); return 2; }
  PD_ConfigDestroy(cfg);

  int n_in = PD_PredictorGetInputNum(pred);
  printf("inputs %d\n", n_in);

  /* 2x8 input filled with i*0.125 */
  float data[16];
  for (int i = 0; i < 16; ++i) data[i] = (float)i * 0.125f;
  int64_t shape[2] = {2, 8};
  PD_Tensor* in = PD_PredictorGetInputHandle(
      pred, PD_PredictorGetInputName(pred, 0));
  PD_TensorReshape(in, 2, shape);
  PD_TensorCopyFromCpuFloat(in, data);

  if (PD_PredictorRun(pred) != 0) {
    fprintf(stderr, "run: %s\n", PD_GetLastError());
    return 3;
  }
  PD_Tensor* out = PD_PredictorGetOutputHandle(
      pred, PD_PredictorGetOutputName(pred, 0));
  int nd = PD_TensorGetNumDims(out);
  int64_t oshape[8];
  PD_TensorGetShape(out, oshape);
  long long total = 1;
  for (int i = 0; i < nd; ++i) total *= oshape[i];
  float* obuf = (float*)malloc(sizeof(float) * total);
  PD_TensorCopyToCpuFloat(out, obuf);
  printf("out %d dims:", nd);
  for (int i = 0; i < nd; ++i) printf(" %lld", (long long)oshape[i]);
  printf("\n");
  for (long long i = 0; i < total; ++i) printf("%.6f\n", obuf[i]);

  /* per-thread clone: serve from a SECOND thread (the GIL must be
     parked by the library or this deadlocks) */
  PD_Predictor* clone = PD_PredictorClone(pred);
  struct CloneJob job;
  job.pred = clone;
  job.total = total;
  job.buf = (float*)malloc(sizeof(float) * total);
  pthread_t th;
  if (pthread_create(&th, NULL, run_clone, &job) != 0) return 4;
  if (pthread_join(th, NULL) != 0) return 4;
  if (job.rc != 0) { fprintf(stderr, "clone thread rc=%d\n", job.rc); return 4; }
  printf("CLONE\n");
  for (long long i = 0; i < total; ++i) printf("%.6f\n", job.buf[i]);
  float* cbuf = job.buf;

  free(obuf);
  free(cbuf);
  PD_PredictorDestroy(clone);
  PD_PredictorDestroy(pred);
  return 0;
}
"""


@pytest.fixture(scope="module")
def capi_lib(tmp_path_factory):
    build = tmp_path_factory.mktemp("capi")
    lib = build / "libpaddle_tpu_infer.so"
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC",
        os.path.join(CSRC, "paddle_tpu_infer_capi.cc"),
        f"-I{inc}", f"-L{libdir}", f"-l{ver}", "-ldl", "-lm",
        "-o", str(lib),
    ]
    r = subprocess.run(cmd, capture_output=True)
    if r.returncode != 0:
        pytest.skip(f"embedding toolchain unavailable: {r.stderr.decode()[:400]}")
    return lib, libdir


def test_c_client_matches_python_predictor(tmp_path, capi_lib):
    lib, libdir = capi_lib
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
    net.eval()
    prefix = os.path.join(str(tmp_path), "model")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([2, 8], "float32")])

    src = tmp_path / "client.c"
    src.write_text(C_CLIENT)
    exe = tmp_path / "client"
    r = subprocess.run(
        ["g++", "-O2", str(src), f"-I{CSRC}", f"-L{lib.parent}",
         "-lpaddle_tpu_infer", "-lpthread", "-o", str(exe)],
        capture_output=True)
    assert r.returncode == 0, r.stderr.decode()[:500]

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join([REPO] + [p for p in sys.path if p])
    env["LD_LIBRARY_PATH"] = os.pathsep.join(
        [str(lib.parent), libdir, env.get("LD_LIBRARY_PATH", "")])
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    r = subprocess.run([str(exe), prefix], capture_output=True, env=env,
                       timeout=300)
    assert r.returncode == 0, (r.stdout.decode()[-500:],
                               r.stderr.decode()[-1500:])
    lines = r.stdout.decode().splitlines()
    assert lines[0] == "inputs 1"
    assert lines[1].startswith("out 2 dims: 2 4")
    clone_at = lines.index("CLONE")
    got = np.asarray([float(v) for v in lines[2:clone_at]]).reshape(2, 4)
    got_clone = np.asarray(
        [float(v) for v in lines[clone_at + 1:]]).reshape(2, 4)

    x = (np.arange(16, dtype=np.float32) * 0.125).reshape(2, 8)
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    ref_clone = net(paddle.to_tensor(np.ones((2, 8), "f4"))).numpy()
    np.testing.assert_allclose(got_clone, ref_clone, rtol=1e-5, atol=1e-5)
