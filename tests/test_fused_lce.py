"""fused_linear_cross_entropy: chunked fused lm-head+CE must be
numerically identical to the unfused logits path (loss AND grads), in
and out of jit, packed and dense."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.nn.functional import fused_linear_cross_entropy


def _data(n=50, h=16, v=37, seed=0):
    r = np.random.RandomState(seed)
    hid = r.randn(n, h).astype(np.float32)
    w = (r.randn(h, v) * 0.1).astype(np.float32)
    y = r.randint(0, v, (n,)).astype(np.int64)
    return hid, w, y


def test_fused_lce_matches_unfused_loss_and_grads():
    hid_np, w_np, y_np = _data()
    # some ignored rows
    y_np[[3, 7]] = -100

    def run(fused):
        hid = paddle.to_tensor(hid_np)
        w = paddle.to_tensor(w_np)
        hid.stop_gradient = False
        w.stop_gradient = False
        if fused:
            loss = fused_linear_cross_entropy(
                hid, w, paddle.to_tensor(y_np), chunk_rows=16)
        else:
            logits = paddle.matmul(hid, w)
            loss = F.cross_entropy(logits, paddle.to_tensor(y_np))
        loss.backward()
        return float(loss), np.asarray(hid.grad._value), \
            np.asarray(w.grad._value)

    l0, gh0, gw0 = run(False)
    l1, gh1, gw1 = run(True)
    np.testing.assert_allclose(l1, l0, rtol=1e-6)
    np.testing.assert_allclose(gh1, gh0, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(gw1, gw0, rtol=1e-5, atol=1e-7)


def test_fused_lce_pads_non_divisible_rows():
    hid_np, w_np, y_np = _data(n=23)
    loss_ref = float(F.cross_entropy(
        paddle.matmul(paddle.to_tensor(hid_np), paddle.to_tensor(w_np)),
        paddle.to_tensor(y_np)))
    loss = float(fused_linear_cross_entropy(
        paddle.to_tensor(hid_np), paddle.to_tensor(w_np),
        paddle.to_tensor(y_np), chunk_rows=8))
    np.testing.assert_allclose(loss, loss_ref, rtol=1e-6)


def test_fused_lce_bias():
    hid_np, w_np, y_np = _data(n=32)
    b_np = np.random.RandomState(5).randn(w_np.shape[1]).astype(np.float32)
    logits = paddle.matmul(paddle.to_tensor(hid_np), paddle.to_tensor(w_np)) \
        + paddle.to_tensor(b_np)
    loss_ref = float(F.cross_entropy(logits, paddle.to_tensor(y_np)))
    loss = float(fused_linear_cross_entropy(
        paddle.to_tensor(hid_np), paddle.to_tensor(w_np),
        paddle.to_tensor(y_np), bias=paddle.to_tensor(b_np), chunk_rows=8))
    np.testing.assert_allclose(loss, loss_ref, rtol=1e-6)


@pytest.mark.xfail(
    reason="pre-existing under this container's jax: XLA donation "
           "aliases a replicated param buffer to an mp-resharded "
           "output ('Expected aliased input ... to have the same "
           "size') in the dp4xmp2 hybrid step; present at seed",
    strict=False)
def test_fused_lce_under_tensor_parallel_matches_serial():
    """The fused criterion composed with TP (mp2 x dp) on the 8-device
    mesh: the llama model's mp-sharded layers + fused lm-head+CE must
    reproduce the mesh-less serial fused run AND the serial unfused run
    over 2 jitted train steps — the hybrid-parallel pretrain recipe the
    north-star config would use."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.parallel import mesh as mesh_state
    from paddle_tpu.nlp import (
        LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    )
    from paddle_tpu.jit.train import JittedTrainStep

    ids_np = np.random.RandomState(0).randint(0, 128, (4, 32))

    def run(mesh, fuse):
        mesh_state.set_mesh(None)
        try:
            if mesh:
                strategy = fleet.DistributedStrategy()
                strategy.hybrid_configs = {
                    "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
                    "sharding_degree": 1,
                }
                fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            cfg = LlamaConfig.tiny(tensor_parallel=True,
                                   fuse_linear_cross_entropy=fuse)
            model = LlamaForCausalLM(cfg)
            crit = LlamaPretrainingCriterion(
                cfg, lm_head=model.lm_head if fuse else None)
            opt = paddle.optimizer.AdamW(1e-3,
                                         parameters=model.parameters())
            step = JittedTrainStep(model, lambda o, l: crit(o, l), opt)
            ids = paddle.to_tensor(ids_np)
            return [float(step(ids, ids)) for _ in range(2)]
        finally:
            # a mid-step failure must not leak the dp4xmp2 mesh into
            # later tests' device_put placements
            mesh_state.set_mesh(None)

    serial_unfused = run(False, False)
    serial_fused = run(False, True)
    tp_fused = run(True, True)
    np.testing.assert_allclose(serial_fused, serial_unfused,
                               rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(tp_fused, serial_fused,
                               rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("packed", [False, True])
def test_llama_fused_criterion_matches_unfused_train(packed):
    """Two jitted train steps at tiny shape: fused-loss config must track
    the unfused config's losses exactly (same seed, same data)."""
    from paddle_tpu.nlp import (
        LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    )
    from paddle_tpu.jit.train import JittedTrainStep

    ids_np = np.random.RandomState(0).randint(0, 128, (1 if packed else 2, 64))
    cu = np.asarray([0, 20, 45, 64], np.int32) if packed else None

    def run(fuse):
        paddle.seed(0)
        cfg = LlamaConfig.tiny(tensor_parallel=False,
                               fuse_linear_cross_entropy=fuse)
        model = LlamaForCausalLM(cfg)
        crit = LlamaPretrainingCriterion(
            cfg, lm_head=model.lm_head if fuse else None)
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
        if packed:
            cu_t = paddle.to_tensor(cu)

            def criterion(out, labels):
                return crit(out, labels, cu_seqlens=cu_t)

            import types

            orig_forward = model.forward
            model.forward = types.MethodType(
                lambda self, x: orig_forward(x, cu_seqlens=cu_t), model)
        else:
            def criterion(out, labels):
                return crit(out, labels)
        step = JittedTrainStep(model, criterion, opt)
        ids = paddle.to_tensor(ids_np)
        return [float(step(ids, ids)) for _ in range(2)]

    ref = run(False)
    got = run(True)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)
