"""paddle.distribution / paddle.signal / paddle.geometric namespaces
(SURVEY.md §2.4 API breadth), scipy/numpy-oracle checked."""
import numpy as np
import pytest
from scipy import stats as sps

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestDistributions:
    def test_normal_logprob_entropy_kl(self):
        n = D.Normal(_t(np.float32(1.0)), _t(np.float32(2.0)))
        v = np.array([0.0, 1.0, 3.0], "f4")
        np.testing.assert_allclose(
            np.asarray(n.log_prob(_t(v))._value),
            sps.norm.logpdf(v, 1.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(
            float(n.entropy()), sps.norm.entropy(1.0, 2.0), rtol=1e-5)
        m = D.Normal(_t(np.float32(0.0)), _t(np.float32(1.0)))
        # KL(N(1,2)||N(0,1)) analytic
        expect = 0.5 * (4 + 1 - 1 - np.log(4))
        np.testing.assert_allclose(
            float(D.kl_divergence(n, m)), expect, rtol=1e-5)

    def test_normal_rsample_reparameterized_grads(self):
        paddle.seed(0)
        loc = _t(np.float32(0.5))
        loc.stop_gradient = False
        dist = D.Normal(loc, _t(np.float32(1.0)))
        s = dist.rsample([256])
        (g,) = paddle.grad(s.mean(), [loc])
        np.testing.assert_allclose(float(g), 1.0, rtol=1e-5)

    def test_sampling_statistics(self):
        paddle.seed(0)
        u = D.Uniform(_t(np.float32(-1.0)), _t(np.float32(3.0)))
        s = np.asarray(u.sample([4000])._value)
        assert -1 <= s.min() and s.max() < 3
        assert abs(s.mean() - 1.0) < 0.1

        b = D.Bernoulli(probs=_t(np.float32(0.3)))
        s = np.asarray(b.sample([4000])._value)
        assert abs(s.mean() - 0.3) < 0.05

    def test_categorical(self):
        logits = _t(np.log(np.array([0.1, 0.2, 0.7], "f4")))
        c = D.Categorical(logits)
        np.testing.assert_allclose(
            np.asarray(c.probs._value), [0.1, 0.2, 0.7], rtol=1e-5)
        np.testing.assert_allclose(
            float(c.log_prob(_t(np.int64(2)))), np.log(0.7), rtol=1e-5)
        expect_h = -(np.array([0.1, 0.2, 0.7])
                     * np.log([0.1, 0.2, 0.7])).sum()
        np.testing.assert_allclose(float(c.entropy()), expect_h, rtol=1e-5)
        c2 = D.Categorical(_t(np.zeros(3, "f4")))
        kl = float(D.kl_divergence(c, c2))
        assert kl > 0

    def test_beta_dirichlet_gumbel_laplace(self):
        bt = D.Beta(_t(np.float32(2.0)), _t(np.float32(3.0)))
        v = np.array([0.2, 0.5], "f4")
        np.testing.assert_allclose(
            np.asarray(bt.log_prob(_t(v))._value),
            sps.beta.logpdf(v, 2.0, 3.0), rtol=1e-4)
        np.testing.assert_allclose(float(bt.mean), 0.4, rtol=1e-6)

        dr = D.Dirichlet(_t(np.array([1.0, 2.0, 3.0], "f4")))
        x = np.array([0.2, 0.3, 0.5], "f4")
        np.testing.assert_allclose(
            float(dr.log_prob(_t(x))),
            sps.dirichlet.logpdf(x, [1.0, 2.0, 3.0]), rtol=1e-4)

        lp = D.Laplace(_t(np.float32(0.0)), _t(np.float32(1.0)))
        np.testing.assert_allclose(
            float(lp.log_prob(_t(np.float32(1.0)))),
            sps.laplace.logpdf(1.0), rtol=1e-5)

        gm = D.Gumbel(_t(np.float32(0.0)), _t(np.float32(1.0)))
        np.testing.assert_allclose(
            float(gm.log_prob(_t(np.float32(0.5)))),
            sps.gumbel_r.logpdf(0.5), rtol=1e-4)


class TestSignal:
    def test_stft_matches_manual_dft(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 256).astype("f4")
        n_fft, hop = 64, 16
        spec = paddle.signal.stft(
            _t(x), n_fft, hop_length=hop, center=False)
        got = np.asarray(spec._value)
        # manual: frame + rfft
        n_frames = (256 - n_fft) // hop + 1
        for f in range(0, n_frames, 3):
            ref = np.fft.rfft(x[0, f * hop: f * hop + n_fft])
            np.testing.assert_allclose(
                got[0, :, f], ref.astype("c8"), rtol=1e-3, atol=1e-3)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(1)
        x = rng.randn(192).astype("f4")
        n_fft, hop = 64, 16
        win = np.hanning(n_fft).astype("f4")
        spec = paddle.signal.stft(
            _t(x), n_fft, hop_length=hop, window=_t(win), center=True)
        back = paddle.signal.istft(
            spec, n_fft, hop_length=hop, window=_t(win), center=True,
            length=192)
        np.testing.assert_allclose(
            np.asarray(back._value), x, rtol=1e-3, atol=1e-3)


class TestGeometric:
    def test_segment_ops(self):
        data = _t(np.array([[1.0, 2], [3, 4], [5, 6], [7, 8]], "f4"))
        ids = _t(np.array([0, 0, 1, 1], "i4"))
        np.testing.assert_allclose(
            np.asarray(paddle.geometric.segment_sum(data, ids)._value),
            [[4, 6], [12, 14]])
        np.testing.assert_allclose(
            np.asarray(paddle.geometric.segment_mean(data, ids)._value),
            [[2, 3], [6, 7]])
        np.testing.assert_allclose(
            np.asarray(paddle.geometric.segment_max(data, ids)._value),
            [[3, 4], [7, 8]])
        np.testing.assert_allclose(
            np.asarray(paddle.geometric.segment_min(data, ids)._value),
            [[1, 2], [5, 6]])

    def test_send_u_recv_and_grads(self):
        x = _t(np.array([[1.0], [2.0], [3.0]], "f4"))
        x.stop_gradient = False
        src = _t(np.array([0, 1, 2, 0], "i4"))
        dst = _t(np.array([1, 2, 0, 2], "i4"))
        out = paddle.geometric.send_u_recv(x, src, dst, reduce_op="sum")
        np.testing.assert_allclose(
            np.asarray(out._value), [[3.0], [1.0], [3.0]])
        out.sum().backward()
        # node 0 sent twice, others once
        np.testing.assert_allclose(
            np.asarray(x.grad._value), [[2.0], [1.0], [1.0]])

    def test_send_ue_recv_mean_and_empty_buckets(self):
        x = _t(np.array([[1.0], [2.0]], "f4"))
        e = _t(np.array([[10.0], [20.0]], "f4"))
        src = _t(np.array([0, 1], "i4"))
        dst = _t(np.array([0, 0], "i4"))
        out = paddle.geometric.send_ue_recv(
            x, e, src, dst, message_op="add", reduce_op="mean", out_size=2)
        np.testing.assert_allclose(
            np.asarray(out._value), [[16.5], [0.0]])
        out2 = paddle.geometric.send_u_recv(
            x, src, dst, reduce_op="max", out_size=2)
        np.testing.assert_allclose(
            np.asarray(out2._value), [[2.0], [0.0]])  # empty bucket → 0


def test_segment_max_empty_buckets_zeroed():
    data = _t(np.array([[1.0], [2.0]], "f4"))
    ids = _t(np.array([0, 0], "i4"))
    out = paddle.geometric.segment_max(data, ids, num_segments=3)
    np.testing.assert_allclose(
        np.asarray(out._value), [[2.0], [0.0], [0.0]])
    out = paddle.geometric.segment_min(data, ids, num_segments=3)
    np.testing.assert_allclose(
        np.asarray(out._value), [[1.0], [0.0], [0.0]])


def test_segment_name_kwarg_accepted():
    data = _t(np.ones((2, 2), "f4"))
    ids = _t(np.array([0, 1], "i4"))
    paddle.geometric.segment_sum(data, ids, name="s")


def test_stft_rectangular_win_length():
    rng = np.random.RandomState(2)
    x = rng.randn(128).astype("f4")
    n_fft, win, hop = 64, 32, 16
    spec = paddle.signal.stft(
        _t(x), n_fft, hop_length=hop, win_length=win, center=False)
    got = np.asarray(spec._value)[:, 0]
    # reference: rectangular win_length window centered in the frame
    w = np.zeros(n_fft, "f4")
    w[(n_fft - win) // 2: (n_fft - win) // 2 + win] = 1.0
    ref = np.fft.rfft(x[:n_fft] * w)
    np.testing.assert_allclose(got, ref.astype("c8"), rtol=1e-3, atol=1e-3)


def test_istft_return_complex_validation():
    spec = paddle.signal.stft(_t(np.random.randn(128).astype("f4")), 32)
    with pytest.raises(ValueError, match="onesided"):
        paddle.signal.istft(spec, 32, return_complex=True, onesided=True)
