"""Adversarial tests for content-addressed prefix caching + COW block
sharing in the paged KV pool (ISSUE 9).

Pool level: forced hash collisions must verify before aliasing, chain
depth is part of the key, copy-on-write isolates writers at the device
rows, eviction respects refcounts (LRU, leaf-first, never a live
holder), the refcount-aware fragmentation stats count a shared block
once while reducing exactly to the old sums on unshared pools, and a
100-round seeded ragged churn leaks nothing at refcount granularity.

Engine level: a prefix-cached engine's streams are BIT-IDENTICAL to
the unshared engine (greedy and fixed-seed sampling, including the
full-prompt-match requests whose capped re-prefill forces COW),
admission counts only NOVEL block demand (same-prompt requests run
concurrently where the unshared engine must serialize), and evicting
one sharer mid-decode leaves both the survivor and the resumed stream
bit-exact.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp import PagedKVCachePool
from paddle_tpu.nlp import paged_cache
from paddle_tpu.serving import ServingEngine


def _pool(num_blocks=8, bs=4, prefix=True):
    return PagedKVCachePool(num_blocks=num_blocks, block_size=bs,
                            num_kv_heads=2, head_dim=8,
                            dtype=jnp.float32, prefix_cache=prefix)


def _audit(pool):
    """Refcount-granularity leak oracle: every block's refcount must
    equal its holder count (tables mapping it + the index's hold), the
    free list and the held set must partition the pool, and the stats
    must stay sane."""
    expect = {}
    for table in pool._tables.values():
        for b in table:
            expect[b] = expect.get(b, 0) + 1
    for b in pool._cached_blocks:
        expect[b] = expect.get(b, 0) + 1
    assert expect == pool._refcounts
    assert len(pool._free) + len(expect) == pool.num_blocks
    assert not (set(pool._free) & set(expect))
    st = pool.fragmentation_stats()
    assert 0.0 <= st["utilization"] <= 1.0
    assert st["blocks_in_use"] == len(expect)


# ------------------------------------------------------- hash chaining
def test_chain_depth_is_part_of_the_key():
    """The SAME block content at different prefix depths must index as
    distinct entries (the rolling hash chains over the parent), and a
    prompt whose first block differs matches nothing even though its
    second block's content is cached at depth 1."""
    pool = _pool(num_blocks=8, bs=4)
    rep = np.array([7, 7, 7, 7] * 2, np.int32)  # block A twice
    pool.ensure("a", 8)
    assert pool.publish_prefix("a", rep) == 2
    e0, e1 = pool._match_entries(rep)
    assert e0.block != e1.block and e0.hash != e1.hash
    assert e1.parent is e0
    # depth-0 content alone matches one block, not two
    assert pool.match_prefix(np.array([7, 7, 7, 7, 1, 2, 3, 4],
                                      np.int32)) == 4
    # block A at depth 1 behind a different head: no match at all
    assert pool.match_prefix(np.array([9, 9, 9, 9, 7, 7, 7, 7],
                                      np.int32)) == 0


def test_forced_hash_collision_never_aliases(monkeypatch):
    """Break the hash entirely (every block keys to the same bucket):
    lookups must STILL never alias — bucket entries verify parent
    identity + the stored token tuple before any share."""
    monkeypatch.setattr(paged_cache, "_chain_hash",
                        lambda parent_hash, tokens: 7)
    pool = _pool(num_blocks=12, bs=4)
    p1 = np.arange(8, dtype=np.int32)
    p2 = np.arange(8, 16, dtype=np.int32)
    pool.ensure("a", 8)
    pool.ensure("b", 8)
    assert pool.publish_prefix("a", p1) == 2
    assert pool.publish_prefix("b", p2) == 2
    assert len(pool._prefix_buckets) == 1  # all four entries, one bucket
    got = pool.attach_prefix("c", p2)
    assert got == 8
    assert pool._tables["c"] == pool._tables["b"]
    assert pool._tables["c"] != pool._tables["a"]
    # content cached under neither chain: verified miss, no alias
    assert pool.attach_prefix("d", np.full(8, 99, np.int32)) == 0
    _audit(pool)


# ------------------------------------------------------- copy-on-write
def test_cow_isolates_writers_at_device_rows():
    """A write into a shared block must land in a FRESH copy: the
    sharer's (and the index's) block keeps its rows bit-exact, the
    writer's table swaps to the copy, refcounts rebalance."""
    pool = _pool(num_blocks=8, bs=4)
    toks = np.arange(8, dtype=np.int32)
    pool.ensure("a", 8)
    k = pool.k_pools[0]
    for blk in pool._tables["a"]:
        k = k.at[blk].set(float(blk) + 1.0)
    pool.k_pools[0] = k
    pool.publish_prefix("a", toks)
    assert pool.attach_prefix("b", toks) == 8
    shared = list(pool._tables["b"])
    assert shared == pool._tables["a"]
    before = [np.asarray(pool.k_pools[0][b]) for b in shared]
    copies = pool.make_writable("b", 4, 8)  # write into block 1 only
    assert copies == 1
    assert pool._tables["b"][0] == shared[0]      # untouched: still shared
    fresh = pool._tables["b"][1]
    assert fresh != shared[1]
    # the copy carries the rows; the original is untouched
    np.testing.assert_array_equal(np.asarray(pool.k_pools[0][fresh]),
                                  before[1])
    np.testing.assert_array_equal(np.asarray(pool.k_pools[0][shared[1]]),
                                  before[1])
    pool.k_pools[0] = pool.k_pools[0].at[fresh].set(-1.0)
    np.testing.assert_array_equal(np.asarray(pool.k_pools[0][shared[1]]),
                                  before[1])
    assert pool._refcounts[shared[1]] == 2  # a + index (b moved off)
    assert pool._refcounts[fresh] == 1
    # exclusively-owned fast path: second write copies nothing
    assert pool.make_writable("b", 4, 8) == 0
    assert pool.cow_copies == 1
    _audit(pool)


# ------------------------------------------------------------ eviction
def test_eviction_respects_refcounts_lru_leaf_first():
    pool = _pool(num_blocks=8, bs=4)
    old = np.arange(8, dtype=np.int32)
    new = np.arange(100, 108, dtype=np.int32)
    pool.ensure("a", 8)
    pool.publish_prefix("a", old)
    pool.ensure("b", 8)
    pool.publish_prefix("b", new)       # later tick than "a"'s chain
    # live holders pin everything: nothing is evictable
    assert pool.evictable_prefix_blocks() == 0
    assert pool.evict_prefix(8) == 0
    pool.free("a")
    pool.free("b")
    assert pool.evictable_prefix_blocks() == 4
    # LRU leaf-first: the OLD chain's leaf (depth 1) goes first,
    # leaving its depth-0 parent cached and the chain walkable
    assert pool.evict_prefix(1) == 1
    assert pool.match_prefix(old) == 4
    assert pool.match_prefix(new) == 8
    # attaching re-pins: the survivor chain can't be evicted under it
    pool.attach_prefix("c", new)
    assert pool.evict_prefix(8) == 1    # only old's depth-0 leaf left
    assert pool.cached_blocks == 2
    _audit(pool)


def test_allocation_pressure_reclaims_cached_only_blocks():
    """ensure() on a dry free list must evict cached-only blocks on
    demand — and must STILL raise exhaustion when live sequences pin
    the rest."""
    pool = _pool(num_blocks=4, bs=4)
    toks = np.arange(8, dtype=np.int32)
    pool.ensure("a", 8)
    pool.publish_prefix("a", toks)
    pool.free("a")                       # 2 cached-only + 2 free
    assert pool.can_allocate(16)
    pool.ensure("big", 16)               # needs all 4: evicts the cache
    assert pool.cached_blocks == 0
    assert pool.prefix_evictions == 2
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.ensure("more", 4)
    _audit(pool)


def test_clear_prefix_cache_releases_every_hold():
    pool = _pool(num_blocks=8, bs=4)
    toks = np.arange(12, dtype=np.int32)
    pool.ensure("a", 12)
    pool.publish_prefix("a", toks)
    pool.free("a")
    assert pool.cached_blocks == 3
    assert pool.clear_prefix_cache() == 3
    assert pool.free_blocks == pool.num_blocks
    assert not pool._refcounts and not pool._prefix_buckets
    _audit(pool)


# ------------------------------------- refcount-aware fragmentation
def test_fragmentation_counts_shared_block_once():
    """Three holders of the same two physical blocks (publisher, index,
    attacher) must report 2 blocks in use at utilization 1.0 — the
    per-sequence sum would claim 16 live tokens over 8 slots."""
    pool = _pool(num_blocks=8, bs=4)
    toks = np.arange(8, dtype=np.int32)
    pool.ensure("a", 8)
    pool.publish_prefix("a", toks)
    pool.attach_prefix("b", toks)
    s = pool.fragmentation_stats()
    assert s["blocks_in_use"] == 2
    assert s["live_tokens"] == 8
    assert s["utilization"] == pytest.approx(1.0)
    assert s["shared_blocks"] == 2
    assert s["cached_blocks"] == 2
    # a cached-only block (holders freed) still counts as fully live
    pool.free("a")
    pool.free("b")
    s2 = pool.fragmentation_stats()
    assert s2["blocks_in_use"] == 2
    assert s2["utilization"] == pytest.approx(1.0)
    assert s2["shared_blocks"] == 0
    _audit(pool)


def test_fragmentation_unshared_pool_unchanged():
    """Regression pin: with the prefix index enabled but no sharing,
    the refcount-aware stats reduce EXACTLY to the legacy per-sequence
    sums (same numbers test_serving pins on a plain pool)."""
    pool = _pool(bs=4)
    pool.ensure("a", 5)
    pool.ensure("b", 4)
    s = pool.fragmentation_stats()
    assert s["blocks_in_use"] == 3
    assert s["live_tokens"] == 9
    assert s["tail_waste_tokens"] == 3
    assert s["utilization"] == pytest.approx(9 / 12)
    assert s["shared_blocks"] == 0 and s["cached_blocks"] == 0


def test_stats_snapshot_during_active_cow_stays_consistent():
    """Regression pin (ISSUE 10, alongside the shared-counted-once
    pin): a stats snapshot taken INSIDE make_writable's allocate-then-
    copy window — right after the fresh block leaves the free list,
    before the table swap and the old block's decref — must not
    double-count the in-flight block. Before refcount-at-birth,
    ``blocks_in_use`` already included the fresh block while the
    refcount map did not, so the accounting the two stats methods
    publish disagreed mid-COW."""
    pool = _pool(num_blocks=8, bs=4)
    toks = np.arange(8, dtype=np.int32)
    pool.ensure("a", 8)
    pool.publish_prefix("a", toks)
    pool.attach_prefix("b", toks)

    snaps = []
    orig = pool._alloc_block

    def alloc_then_snapshot():
        blk = orig()
        # mid-COW: fresh block allocated, device copy / table swap /
        # old-block decref still pending
        snaps.append((pool.fragmentation_stats(),
                      pool.prefix_cache_stats()))
        return blk

    pool._alloc_block = alloc_then_snapshot
    copied = pool.make_writable("b", 0, 8)
    pool._alloc_block = orig
    assert copied == 2 and len(snaps) == 2
    for frag, pref in snaps:
        assert 0.0 <= frag["utilization"] <= 1.0
        assert frag["blocks_in_use"] <= pool.num_blocks
        assert pref["cached_blocks"] == 2
    _audit(pool)


def test_stats_raise_on_accounting_drift():
    """The consistency gate itself: corrupting the ownership
    structures makes BOTH stats methods raise instead of publishing
    numbers built on corrupt accounting."""
    pool = _pool(num_blocks=8, bs=4)
    pool.ensure("a", 8)
    blk = pool._tables["a"][0]
    held = pool._refcounts.pop(blk)  # an allocated-but-untracked block
    with pytest.raises(RuntimeError, match="accounting drift"):
        pool.fragmentation_stats()
    with pytest.raises(RuntimeError, match="accounting drift"):
        pool.prefix_cache_stats()
    # same count, wrong identity: a FREE block refcounted in place of
    # the held one trips the free/held overlap check instead
    pool._refcounts[pool._free[-1]] = 1
    with pytest.raises(RuntimeError, match="free and refcounted"):
        pool.fragmentation_stats()
    del pool._refcounts[pool._free[-1]]
    pool._refcounts[blk] = held
    _audit(pool)


# ------------------------------------------------------- ragged churn
def test_pool_ragged_churn_100_rounds_zero_leaks():
    """100 seeded rounds of ragged admit/attach/publish/COW/trim/free/
    evict over a tiny token alphabet (so chains really share), with the
    refcount-granularity audit after EVERY round; teardown must return
    the pool to pristine."""
    rng = np.random.RandomState(42)
    pool = _pool(num_blocks=16, bs=4)
    live, counter = {}, 0
    for _ in range(100):
        op = rng.rand()
        if op < 0.55 and len(live) < 6:
            sid = f"s{counter}"
            counter += 1
            toks = rng.randint(0, 3,
                               rng.randint(1, 21)).astype(np.int32)
            try:
                matched = pool.attach_prefix(sid, toks)
                pool.ensure(sid, len(toks))
                if rng.rand() < 0.25:
                    # rewrite-from-scratch: COW every shared block
                    pool.make_writable(sid, 0, len(toks))
                else:
                    pool.make_writable(sid, matched, len(toks))
                pool.publish_prefix(sid, toks)
                live[sid] = toks
            except RuntimeError:
                pool.free(sid)  # exhausted mid-growth: roll back
                if live:
                    victim = list(live)[rng.randint(len(live))]
                    live.pop(victim)
                    pool.free(victim)
        elif op < 0.75 and live:
            victim = list(live)[rng.randint(len(live))]
            live.pop(victim)
            pool.free(victim)
        elif op < 0.85 and live:
            sid = list(live)[rng.randint(len(live))]
            keep = rng.randint(0, len(live[sid]) + 1)
            pool.trim(sid, keep)
        else:
            pool.evict_prefix(rng.randint(0, 3))
        _audit(pool)
    assert pool.prefix_hits > 0 and pool.cow_copies > 0
    assert pool.prefix_evictions > 0
    for sid in list(live):
        pool.free(sid)
    pool.clear_prefix_cache()
    assert pool.free_blocks == pool.num_blocks
    assert not pool._refcounts and not pool._tables
    assert not pool._prefix_buckets and not pool._cached_blocks


# ------------------------------------------------------- engine parity
@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


def _shared_prompts(cfg):
    """A common 8-token system prompt (2 full blocks at bs=4) + unique
    tails; the LAST prompt is the bare system prompt — its full-chain
    hit re-prefills one capped token into a shared block, the designed
    COW trigger."""
    rng = np.random.RandomState(3)
    sys_p = rng.randint(1, cfg.vocab_size, 8).astype(np.int32)
    tails = [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
             for n in (3, 5, 1)]
    return [np.concatenate([sys_p, t]) for t in tails] + [sys_p.copy()]


def _run_engine(model, prompts, max_new, prefix, seeds=None, **kw):
    eng = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        prefix_cache=prefix, **kw)
    for i, (p, mn) in enumerate(zip(prompts, max_new)):
        eng.submit(p, max_new_tokens=mn, req_id=f"r{i}",
                   seed=seeds[i] if seeds else 0)
    done = eng.run()
    return eng, {str(r.req_id): list(r.tokens) for r in done}


def test_engine_prefix_greedy_parity(tiny_model):
    """Greedy streams bit-identical to the unshared engine, with real
    hits, at least one COW, and strictly fewer prefill tokens; the
    pool ends clean (scratch + cache only)."""
    cfg, model = tiny_model
    prompts = _shared_prompts(cfg)
    max_new = [5, 4, 6, 4]
    base, want = _run_engine(model, prompts, max_new, prefix=False)
    pref, got = _run_engine(model, prompts, max_new, prefix=True)
    assert got == want
    pc = pref.pool.prefix_cache_stats()
    assert pc["hits"] > 0
    assert pc["cow_copies"] >= 1          # the bare-prompt request
    assert (pref.stats["prefill_tokens"]
            < base.stats["prefill_tokens"])
    assert "prefix_cache" in pref.engine_stats()
    # retirement released every request hold: scratch + cache remain
    assert pref.pool.blocks_in_use == 1 + pref.pool.cached_blocks
    _audit(pref.pool)


def test_engine_admission_counts_novel_demand_only(tiny_model):
    """Two identical prompts on a pool that cannot hold two UNSHARED
    copies: the unshared engine must serialize them, the prefix engine
    admits both at once (the second request's demand is its novel
    blocks) — streams identical either way."""
    cfg, model = tiny_model
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, cfg.vocab_size, 8).astype(np.int32)

    def run(prefix):
        eng = ServingEngine(model, num_slots=2, block_size=4,
                            num_blocks=6, max_context=16,
                            prefill_chunk=4, decode_quantum=3,
                            prefix_cache=prefix)
        a = eng.submit(prompt, max_new_tokens=4, req_id="a")
        # publish the prompt chain, then offer the twin
        while not a.tokens:
            eng.step()
        b = eng.submit(prompt.copy(), max_new_tokens=4, req_id="b")
        overlap = False
        while eng.has_work:
            eng.step()
            overlap = overlap or (a.slot is not None
                                  and b.slot is not None)
        return overlap, {"a": list(a.tokens), "b": list(b.tokens)}

    overlap_u, streams_u = run(False)
    overlap_p, streams_p = run(True)
    assert streams_p == streams_u
    assert not overlap_u   # 1 + 3 + 3 reserved blocks > 6: serialized
    assert overlap_p       # novel demand of the twin fits alongside


@pytest.mark.slow
def test_engine_prefix_sampling_parity(tiny_model):
    """Fixed-seed sampling: the cached engine must replay the unshared
    engine's streams exactly (per-request seeds, shared prefix +
    full-match COW requests included)."""
    cfg, model = tiny_model
    prompts = _shared_prompts(cfg)
    max_new = [5, 4, 6, 4]
    seeds = [101, 202, 303, 404]
    base, want = _run_engine(model, prompts, max_new, prefix=False,
                             seeds=seeds, decode_strategy="sampling",
                             temperature=0.8)
    pref, got = _run_engine(model, prompts, max_new, prefix=True,
                            seeds=seeds, decode_strategy="sampling",
                            temperature=0.8)
    assert got == want
    assert pref.pool.prefix_cache_stats()["hits"] > 0


@pytest.mark.slow
def test_engine_cow_under_preemption(tiny_model):
    """Evict one of two sharers mid-decode: the survivor keeps decoding
    over the still-shared blocks, the victim resumes by re-prefill
    (re-attaching the cache), and BOTH streams stay bit-exact vs an
    undisturbed unshared run."""
    cfg, model = tiny_model
    rng = np.random.RandomState(9)
    sys_p = rng.randint(1, cfg.vocab_size, 8).astype(np.int32)
    prompts = [np.concatenate([sys_p, rng.randint(
        1, cfg.vocab_size, n).astype(np.int32)]) for n in (2, 3)]
    max_new = [8, 8]
    _, want = _run_engine(model, prompts, max_new, prefix=False)

    eng = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        prefix_cache=True)
    a = eng.submit(prompts[0], max_new_tokens=8, req_id="r0")
    b = eng.submit(prompts[1], max_new_tokens=8, req_id="r1")
    while len(a.tokens) < 2 or len(b.tokens) < 2:
        eng.step()
    assert not a.finished and not b.finished
    eng.preempt(a)  # refcount-safe: b and the index keep the prefix
    assert a.slot is None
    done = eng.run()
    got = {str(r.req_id): list(r.tokens) for r in done}
    assert got == want
    assert eng.scheduler.preempted_total == 1
    assert eng.pool.prefix_cache_stats()["hits"] > 0
    _audit(eng.pool)
