"""to_static, save/load, DataLoader tests."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import Dataset, IterableDataset, DataLoader, TensorDataset


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 2)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def test_to_static_matches_eager():
    net = Net()
    x = paddle.randn([3, 4])
    y0 = net(x).numpy()
    snet = paddle.jit.to_static(net)
    y1 = snet(x).numpy()
    np.testing.assert_allclose(y0, y1, rtol=1e-6)


def test_to_static_function():
    @paddle.jit.to_static
    def f(a, b):
        return a * b + a

    out = f(paddle.ones([2]), paddle.to_tensor([2.0, 3.0]))
    np.testing.assert_allclose(out.numpy(), [3.0, 4.0])


def test_to_static_grad_flow():
    net = Net()
    snet = paddle.jit.to_static(net)
    x = paddle.randn([3, 4])
    loss = snet(x).sum()
    loss.backward()
    assert net.fc1.weight.grad is not None
    # grads match eager
    g_static = net.fc1.weight.grad.numpy().copy()
    net.clear_gradients()
    paddle.jit.enable_to_static(False)
    try:
        net(x).sum().backward()
    finally:
        paddle.jit.enable_to_static(True)
    np.testing.assert_allclose(g_static, net.fc1.weight.grad.numpy(), rtol=1e-5)


def test_to_static_retrace_on_shape_change():
    calls = []

    @paddle.jit.to_static
    def f(a):
        calls.append(1)
        return a * 2

    f(paddle.ones([2]))
    f(paddle.ones([2]))  # cached: no retrace
    f(paddle.ones([3]))  # new shape: retrace
    assert len(calls) == 2


def test_stablehlo_export():
    net = Net()
    snet = paddle.jit.to_static(net)
    hlo = snet.forward.get_stablehlo(paddle.randn([2, 4])) if hasattr(
        snet.forward, "get_stablehlo"
    ) else snet(paddle.randn([2, 4]))  # exercise either path
    # direct function form
    sf = paddle.jit.to_static(Net())
    text = sf.forward.get_stablehlo(paddle.randn([2, 4]))
    assert "stablehlo" in text or "module" in text


def test_jit_save_load(tmp_path):
    net = Net()
    net.eval()
    x = paddle.randn([2, 4])
    y_ref = net(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[paddle.static.InputSpec([2, 4])])
    loaded = paddle.jit.load(path)
    y = loaded(x)
    np.testing.assert_allclose(y.numpy(), y_ref, rtol=1e-5)


def test_paddle_save_load_nested(tmp_path):
    obj = {
        "model": Net().state_dict(),
        "step": 7,
        "lr": [0.1, 0.2],
    }
    p = str(tmp_path / "ckpt.pdparams")
    paddle.save(obj, p)
    loaded = paddle.load(p)
    assert loaded["step"] == 7
    k = list(obj["model"])[0]
    np.testing.assert_allclose(
        loaded["model"][k].numpy(), obj["model"][k].numpy()
    )


def test_dataloader_map_dataset():
    class DS(Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            return np.full((2,), i, np.float32), i

    dl = DataLoader(DS(), batch_size=3, drop_last=True)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == [3, 2]
    assert batches[0][1].numpy().tolist() == [0, 1, 2]


def test_dataloader_shuffle_covers_all():
    class DS(Dataset):
        def __len__(self):
            return 20

        def __getitem__(self, i):
            return i

    dl = DataLoader(DS(), batch_size=4, shuffle=True)
    seen = []
    for b in dl:
        seen.extend(b.numpy().tolist())
    assert sorted(seen) == list(range(20))


def test_dataloader_iterable_and_workers():
    class IDS(IterableDataset):
        def __iter__(self):
            yield from (np.float32(i) for i in range(7))

    dl = DataLoader(IDS(), batch_size=2, num_workers=2)
    out = [b.numpy().tolist() for b in dl]
    assert out == [[0, 1], [2, 3], [4, 5], [6]]


def test_tensor_dataset():
    xs = paddle.randn([6, 3])
    ys = paddle.arange(6)
    ds = TensorDataset([xs, ys])
    x0, y0 = ds[2]
    np.testing.assert_allclose(x0.numpy(), xs.numpy()[2])
    dl = DataLoader(ds, batch_size=2)
    bx, by = next(iter(dl))
    assert bx.shape == [2, 3]
