"""vision models + hapi Model + metric tests (config #1 surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.metric import Accuracy, Precision, Recall
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.vision.models import LeNet, resnet18
from paddle_tpu.vision import transforms as T


def test_resnet18_forward_shapes():
    net = resnet18(num_classes=7)
    out = net(paddle.randn([2, 3, 32, 32]))
    assert out.shape == [2, 7]


@pytest.mark.slow  # ~13s (full resnet18 fwd+bwd+opt steps); forward
# shapes + the LeNet hapi fit flow keep the surface covered in tier-1
# — the 870s ceiling forced a re-tier as the suite grew (PR 7)
def test_resnet_train_step_decreases_loss():
    paddle.seed(0)
    net = resnet18(num_classes=4)
    opt = paddle.optimizer.Momentum(0.01, parameters=net.parameters())
    x = paddle.randn([4, 3, 32, 32])
    y = paddle.randint(0, 4, [4])
    losses = []
    for _ in range(5):
        loss = nn.functional.cross_entropy(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_lenet_hapi_fit_improves():
    paddle.seed(0)
    train = FakeData(size=32, image_shape=(1, 28, 28), num_classes=4)
    model = paddle.Model(LeNet(num_classes=4))
    model.prepare(
        paddle.optimizer.Adam(0.01, parameters=model.parameters()),
        nn.CrossEntropyLoss(),
        Accuracy(),
    )
    r0 = model.evaluate(train, batch_size=16, verbose=0)
    model.fit(train, epochs=3, batch_size=16, verbose=0)
    r1 = model.evaluate(train, batch_size=16, verbose=0)
    assert r1["loss"] < r0["loss"]


def test_model_save_load(tmp_path):
    model = paddle.Model(LeNet())
    model.prepare(paddle.optimizer.SGD(0.1, parameters=model.parameters()))
    p = str(tmp_path / "ck")
    model.save(p)
    w_before = model.network.features[0].weight.numpy().copy()
    model.network.features[0].weight.set_value(np.zeros_like(w_before))
    model.load(p)
    np.testing.assert_allclose(
        model.network.features[0].weight.numpy(), w_before
    )


def test_accuracy_metric():
    acc = Accuracy(topk=(1, 2))
    pred = paddle.to_tensor(
        [[0.1, 0.9, 0.0], [0.8, 0.1, 0.1], [0.1, 0.2, 0.7]]
    )
    label = paddle.to_tensor([1, 2, 2])
    correct = acc.compute(pred, label)
    acc.update(correct)
    top1, top2 = acc.accumulate()
    np.testing.assert_allclose(top1, 2 / 3, rtol=1e-6)
    np.testing.assert_allclose(top2, 2 / 3, rtol=1e-6)


def test_precision_recall():
    p, r = Precision(), Recall()
    preds = np.array([0.9, 0.1, 0.8, 0.2])
    labels = np.array([1, 0, 0, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == 0.5  # tp=1 fp=1
    assert r.accumulate() == 0.5  # tp=1 fn=1


def test_transforms_pipeline():
    tf = T.Compose([
        T.Resize(16), T.CenterCrop(12), T.ToTensor(),
        T.Normalize([0.5] * 3, [0.5] * 3),
    ])
    img = np.random.randint(0, 255, (20, 24, 3), np.uint8)
    out = tf(img)
    assert out.shape == [3, 12, 12]
    assert out.dtype.name == "float32"


def test_random_transforms_shapes():
    img = np.random.randint(0, 255, (32, 32, 3), np.uint8)
    assert T.RandomCrop(24)(img).shape == (24, 24, 3)
    assert T.RandomHorizontalFlip(1.0)(img).shape == (32, 32, 3)
    np.testing.assert_array_equal(
        T.RandomHorizontalFlip(1.0)(img), img[:, ::-1]
    )


def test_early_stopping():
    train = FakeData(size=16, image_shape=(1, 28, 28), num_classes=4)
    model = paddle.Model(LeNet(num_classes=4))
    model.prepare(
        paddle.optimizer.SGD(0.0, parameters=model.parameters()),
        nn.CrossEntropyLoss(),
    )
    es = paddle.callbacks.EarlyStopping(monitor="loss", patience=0, mode="min")
    model.fit(train, eval_data=train, epochs=5, batch_size=8, verbose=0,
              callbacks=[es])
    assert model.stop_training
