"""The serving front door (ISSUE 7: the system tier around the
continuous-batching engine — reference: the deployed serving story
around AnalysisPredictor / ``Predictor.run``, PAPER.md §2.6/§3.5):

- policy units: the shedding ladder (ok/warn/critical x priority
  class), queue backpressure, and preemption victim selection — pure
  host logic, no engine.
- the streaming API: sync pull and ``async for`` under ``run_async``,
  per-token delivery matching the request stream exactly, shed streams
  arriving already closed.
- SLO-burn-rate shedding against a forced-critical health report,
  flight-journal capture for shed requests, and the obs overload
  counters.
- the graceful-drain contract: stop admitting (submissions shed with
  reason ``draining``), finish everything accepted, flush the flight
  recorder to schema-valid JSONL.

Engine-level preemption correctness (the bit-exact oracle) lives in
tests/test_serving.py; the full pump-driven preemption e2e is also
exercised by ``python -m paddle_tpu.obs check`` (check_graphs.sh) and
kept ``slow`` here to protect the tier-1 budget. Tests in this file
use ``max_new_tokens=1`` so prefill completion emits the only token
and the jitted decode quantum never compiles."""
import asyncio
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.obs.flight import load_flight_records
from paddle_tpu.serving import (
    BATCH, INTERACTIVE, NORMAL, FrontDoorPolicy, Request,
    ServingEngine, ServingFrontDoor, choose_victim, no_shed_policy,
)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


# ------------------------------------------------ policy units
def test_policy_shedding_ladder():
    pol = FrontDoorPolicy()  # stock: warn sheds BATCH, critical +NORMAL
    assert pol.admission(BATCH, "ok", 0) == (True, None)
    assert pol.admission(BATCH, "warn", 0) == (False, "slo_warn")
    assert pol.admission(NORMAL, "warn", 0) == (True, None)
    assert pol.admission(NORMAL, "critical", 0) == (False,
                                                    "slo_critical")
    # the stock ladder never sheds INTERACTIVE
    assert pol.admission(INTERACTIVE, "critical", 10 ** 6)[0]
    # warn set is implied at critical even if passed disjoint
    pol2 = FrontDoorPolicy(shed_on_warn=(BATCH,),
                           shed_on_critical=(NORMAL,))
    assert pol2.admission(BATCH, "critical", 0) == (False,
                                                    "slo_critical")


def test_policy_backpressure_and_passthrough():
    pol = FrontDoorPolicy(max_waiting=4)
    assert pol.admission(NORMAL, "ok", 3) == (True, None)
    assert pol.admission(NORMAL, "ok", 4) == (False, "backpressure")
    assert pol.admission(INTERACTIVE, "ok", 100) == (True, None)
    ns = no_shed_policy()
    assert ns.admission(BATCH, "critical", 10 ** 6) == (True, None)
    assert ns.preempt is False


def test_choose_victim_rules():
    def req(pri, admit_t, slot=0):
        r = Request(np.arange(1, 4), max_new_tokens=2, priority=pri)
        r.admit_time = admit_t
        r.slot = slot
        return r

    lo_old = req(BATCH, 1.0)
    lo_new = req(BATCH, 2.0)
    mid = req(NORMAL, 0.5)
    live = [mid, lo_old, lo_new]
    # lowest class first, newest admission within the class
    assert choose_victim(live, INTERACTIVE) is lo_new
    assert choose_victim([mid], INTERACTIVE) is mid
    # equal priority never preempts
    assert choose_victim([mid], NORMAL) is None
    # finished / slotless requests are not victims
    mid.finished = True
    lo_old.slot = None
    lo_new.slot = None
    assert choose_victim(live, INTERACTIVE) is None


# ------------------------------------------------ streaming + shed
def test_frontdoor_stream_backpressure_drain(tmp_path, tiny_model):
    """One quantum-free pass over the whole front-door surface:
    sync streaming delivers exactly the emitted tokens, backpressure
    sheds the queue tail (exempting INTERACTIVE), shed streams arrive
    closed with journals captured, drain finishes accepted work,
    refuses new work with reason ``draining``, and flushes schema-valid
    flight JSONL."""
    cfg, model = tiny_model
    rng = np.random.RandomState(0)
    fd = inference.serve(model, num_slots=2, block_size=4,
                         prefill_chunk=8,
                         policy=FrontDoorPolicy(max_waiting=1))
    prompts = [rng.randint(1, cfg.vocab_size, 5).astype(np.int32)
               for _ in range(4)]
    # 2 admit (slots), 1 queues (depth 0 -> ok... depth 1 at 4th), rest
    # shed: submissions see waiting depth 0,1,1,... with max_waiting=1
    s0 = fd.submit(prompts[0], max_new_tokens=1, priority=NORMAL)
    s1 = fd.submit(prompts[1], max_new_tokens=1, priority=NORMAL)
    s2 = fd.submit(prompts[2], max_new_tokens=1, priority=BATCH)
    s3 = fd.submit(prompts[3], max_new_tokens=1,
                   priority=INTERACTIVE)  # exempt from backpressure
    shed = [s for s in (s0, s1, s2, s3) if s.shed]
    kept = [s for s in (s0, s1, s2, s3) if not s.shed]
    assert s2 in shed and s3 not in shed
    for s in shed:
        assert s.closed and list(s) == [] and s.result().size == 0
    # sync streaming: each pull pumps the engine until tokens land
    for s in kept:
        toks = list(s)
        assert toks == s.request.tokens and len(toks) == 1
        assert s.finish_reason == "length"
    # drain: flush journals, then refuse new work
    out = fd.drain(flight_path=str(tmp_path / "flight.jsonl"))
    assert out["drained"] and out["completed"] == len(kept)
    records = load_flight_records(tmp_path / "flight.jsonl")
    shed_recs = [r for r in records
                 if r["events"][-1]["kind"] == "shed"]
    assert len(shed_recs) == len(shed)
    assert all(r["events"][-1]["reason"] == "backpressure"
               for r in shed_recs)
    late = fd.submit(prompts[0], max_new_tokens=1)
    assert late.shed
    assert json.loads(json.dumps(fd.stats()))["draining"] is True
    reg = fd.engine.obs.registry
    assert reg.get("serving_requests_shed_total").value() == \
        len(shed) + 1
    assert reg.get("serving_drains_total").value() == 1


def test_frontdoor_slo_shedding_forced_critical(tiny_model):
    """Burn-rate-driven admission: poison the TTFT sample series so
    both windows burn far past the critical gate — BATCH and NORMAL
    shed with reason ``slo_critical``, INTERACTIVE still admits; the
    health report is cached between submissions."""
    cfg, model = tiny_model
    fd = inference.serve(model, num_slots=2, block_size=4,
                         policy=FrontDoorPolicy(health_interval_s=0.0))
    eng = fd.engine
    now = eng.obs.now()
    # every recent TTFT sample blows the 0.5s stock objective
    eng.obs._series["ttft_seconds"].extend(
        [(now - i * 0.1, 10.0) for i in range(20)])
    assert eng.health(now=now)["state"] == "critical"
    p = np.arange(1, 6, dtype=np.int32)
    assert fd.submit(p, max_new_tokens=1, priority=BATCH).shed
    assert fd.submit(p, max_new_tokens=1, priority=NORMAL).shed
    hi = fd.submit(p, max_new_tokens=1, priority=INTERACTIVE)
    assert not hi.shed
    reasons = {r.req_id: None for r in fd.shed_requests}
    assert len(reasons) == 2
    # shed outcomes burned the error-rate objective too
    outcomes = eng.obs.timeseries()["request_outcomes"]
    assert [v for _, v in outcomes].count(1.0) == 2
    fd.drain()


def test_frontdoor_async_streaming(tiny_model):
    """The asyncio facade: a run_async task pumps the engine while
    consumers ``async for`` their streams; stop() ends the loop."""
    cfg, model = tiny_model
    rng = np.random.RandomState(1)
    fd = inference.serve(model, num_slots=2, block_size=4,
                         prefill_chunk=8)

    async def client(prompt, priority):
        stream = fd.submit(prompt, max_new_tokens=1, priority=priority)
        return [tok async for tok in stream]

    async def main():
        task = asyncio.create_task(fd.run_async(idle_s=0.001))
        outs = await asyncio.gather(
            client(rng.randint(1, cfg.vocab_size, 5)
                   .astype(np.int32), INTERACTIVE),
            client(rng.randint(1, cfg.vocab_size, 7)
                   .astype(np.int32), NORMAL),
            client(rng.randint(1, cfg.vocab_size, 3)
                   .astype(np.int32), BATCH))
        fd.stop()
        await asyncio.wait_for(task, timeout=30)
        return outs

    outs = asyncio.run(main())
    assert [len(o) for o in outs] == [1, 1, 1]
    done = {r.req_id: r for r in fd.engine.completed}
    assert len(done) == 3
    for toks in outs:
        assert all(0 <= t < cfg.vocab_size for t in toks)


def test_serve_facade_wiring(tiny_model):
    """inference.serve(): SLOs + flight recorder default ON, sampling
    auto-enables the per-request quantum variant, one front door per
    engine enforced."""
    cfg, model = tiny_model
    fd = inference.serve(model, num_slots=2, block_size=4)
    assert fd.engine.slo is not None and fd.engine.flight is not None
    assert fd.engine.token_sink is not None
    with pytest.raises(ValueError, match="one front door"):
        ServingFrontDoor(fd.engine)
    fd2 = inference.serve(model, num_slots=2, block_size=4,
                          decode_strategy="sampling", top_k=4)
    assert fd2.engine._per_request_sampling is True
    # engine without SLOs: health reads vacuous ok, shedding rests on
    # backpressure alone
    eng = ServingEngine(model, num_slots=2, block_size=4)
    fd3 = ServingFrontDoor(eng, policy=FrontDoorPolicy())
    assert fd3._health_state(eng.obs.now()) == "ok"


@pytest.mark.slow
def test_frontdoor_pump_preemption_e2e(tiny_model):
    """Pump-driven preemption under slot pressure: an INTERACTIVE
    arrival evicts the newest BATCH victim mid-decode, both finish,
    and the victim's stream continues across the eviction (also
    exercised by `python -m paddle_tpu.obs check` in check_graphs.sh;
    slow-marked to keep the tier-1 compile budget flat)."""
    cfg, model = tiny_model
    rng = np.random.RandomState(2)
    fd = inference.serve(model, num_slots=1, block_size=4,
                         prefill_chunk=4, decode_quantum=2)
    low = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                    .astype(np.int32), max_new_tokens=6,
                    priority=BATCH)
    while len(low.request.tokens) < 2:
        fd.pump()
    hi = fd.submit(rng.randint(1, cfg.vocab_size, 4)
                   .astype(np.int32), max_new_tokens=4,
                   priority=INTERACTIVE)
    fd.run_until_idle()
    assert fd.engine.scheduler.preempted_total == 1
    assert fd.engine.scheduler.resumed_total == 1
    assert len(hi.request.tokens) == 4
    assert len(low.request.tokens) == 6
    assert low.request.preemptions == 1
    assert fd.engine.pool.fragmentation_stats()["blocks_in_use"] == 1


# ------------------------------------------------ resilience (ISSUE 13)
def test_stream_timeout_kwarg(tiny_model):
    """``submit(..., timeout=)`` bounds each token wait: a starved
    stream raises TimeoutError instead of pumping forever, and a
    stream whose tokens keep arriving never notices its timeout."""
    cfg, model = tiny_model
    rng = np.random.RandomState(3)
    # no_shed: the first pump's jit-compile TTFT would otherwise read
    # critical and shed the NORMAL submissions below
    fd = inference.serve(model, num_slots=1, block_size=4,
                         prefill_chunk=8, policy=no_shed_policy())
    busy = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                     .astype(np.int32), max_new_tokens=3,
                     priority=NORMAL)
    starved = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                        .astype(np.int32), max_new_tokens=1,
                        priority=NORMAL, timeout=1e-4)
    with pytest.raises(TimeoutError, match="no token"):
        list(starved)
    # the raise is per-gap, not terminal: once the slot frees, the
    # same stream drains normally
    ok = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                   .astype(np.int32), max_new_tokens=1,
                   priority=NORMAL, timeout=30.0)
    assert list(busy) == busy.request.tokens
    assert len(list(starved)) + len(starved.request.tokens) >= 1
    assert len(list(ok)) == 1
    fd.drain()


def test_quarantined_stream_reaped(tiny_model):
    """A poisoned request emits no closing token — the front door's
    finished-stream reap must close its stream anyway (consumer loop
    ends, finish_reason="error"), while other streams drain normally."""
    from paddle_tpu.serving import FaultInjector

    cfg, model = tiny_model
    rng = np.random.RandomState(4)
    inj = FaultInjector(seed=0)
    fd = inference.serve(model, num_slots=2, block_size=4,
                         prefill_chunk=8, faults=inj, resilience=True)
    good = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                     .astype(np.int32), max_new_tokens=2)
    bad = fd.submit(rng.randint(1, cfg.vocab_size, 7)
                    .astype(np.int32), max_new_tokens=2)
    inj.poison(bad.request.req_id)
    fd.run_until_idle()
    assert bad.request.finish_reason == "error"
    assert bad.closed and list(bad) == []
    assert good.finish_reason == "length"
    assert len(good.request.tokens) == 2
    assert fd.engine.resilience_report()["quarantined"] == [
        str(bad.request.req_id)]
    fd.drain()


def test_pump_failure_fails_open_streams(tiny_model, monkeypatch):
    """A REAL engine exception out of a pump fails every open stream
    terminally (finish_reason="error") and re-raises to the pumping
    consumer — nobody blocks on a dead engine."""
    cfg, model = tiny_model
    rng = np.random.RandomState(5)
    fd = inference.serve(model, num_slots=2, block_size=4,
                         prefill_chunk=8)
    s0 = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                   .astype(np.int32), max_new_tokens=1)
    s1 = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                   .astype(np.int32), max_new_tokens=1)

    def boom():
        raise RuntimeError("engine died")
    monkeypatch.setattr(fd.engine, "step", boom)
    with pytest.raises(RuntimeError, match="engine died"):
        list(s0)
    assert s0.closed and s1.closed
    assert s0.finish_reason == "error" and s1.finish_reason == "error"
    assert fd._streams == {}


def test_orphaned_stream_error_closes(tiny_model):
    """A stream whose request fell out of an IDLE engine closes with
    finish_reason="error" instead of spinning on pump forever."""
    cfg, model = tiny_model
    rng = np.random.RandomState(6)
    fd = inference.serve(model, num_slots=2, block_size=4,
                         prefill_chunk=8)
    s = fd.submit(rng.randint(1, cfg.vocab_size, 5)
                  .astype(np.int32), max_new_tokens=1)
    fd.engine.scheduler.waiting.remove(s.request)   # simulate the drop
    assert list(s) == []
    assert s.closed and s.finish_reason == "error"


def test_frontdoor_snapshot_restore_streams(tiny_model):
    """Crash recovery through the front door: restore() re-opens every
    in-flight stream pre-loaded with its already-emitted tokens, and
    consumers of the restored streams see the FULL bit-exact
    sequences."""
    cfg, model = tiny_model
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, cfg.vocab_size, 5).astype(np.int32)
               for _ in range(2)]
    ref = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=8, decode_quantum=2)
    want = [list(ref.submit(p, max_new_tokens=4).tokens) or None
            for p in prompts]
    ref.run()
    want = [list(r.tokens) for r in ref.completed]

    fd = inference.serve(model, num_slots=2, block_size=4,
                         prefill_chunk=8, decode_quantum=2)
    streams = [fd.submit(p, max_new_tokens=4) for p in prompts]
    while not any(s.request.tokens for s in streams):
        fd.pump()
    snap = json.loads(json.dumps(fd.snapshot()))
    fd2 = ServingFrontDoor.restore(snap, model)
    restored = list(fd2._streams.values())
    assert len(restored) == 2
    got = {str(s.request.req_id): list(s) for s in restored}
    ids = [str(s.request.req_id) for s in streams]
    assert [got[i] for i in ids] == want
    assert all(s.finish_reason == "length" for s in restored)
    fd2.drain()
