"""paddle_tpu.analysis — graph auditor + budget mechanism.

Each IR pass gets a KNOWN-BAD function it must flag and a KNOWN-CLEAN
function it must not, plus the two registered real-recipe budgets
(the TP x ZeRO fused-LCE train step and the on-device greedy decode)
which must hold on the current code — these are the machine-checked
"did not regress the compiled graph" guarantees every future perf PR
inherits."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.parallel import mesh as mesh_state


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def _mesh(shape, axes):
    return Mesh(np.array(jax.devices()).reshape(*shape), axes)


# ---------------------------------------------------------------- census

def test_collective_census_counts_and_bytes():
    mesh = _mesh((8,), ("dp",))

    def step(p, x):
        g = jnp.dot(x, p)
        return p - 0.1 * jnp.dot(x.T, g)

    p = jax.device_put(jnp.zeros((64, 64)), NamedSharding(mesh, P()))
    x = jax.device_put(jnp.ones((8, 64)),
                       NamedSharding(mesh, P("dp")))
    report = analysis.audit(jax.jit(step), p, x)
    # dp grads reduce over the mesh: exactly one all-reduce of the
    # (64, 64) f32 gradient
    st = report.collectives["all-reduce"]
    assert st.count == 1
    assert st.bytes == 64 * 64 * 4
    assert report.collectives["all-gather"].count == 0
    assert report.total_collectives == 1


def test_parse_shape_bytes_tuple_and_scalars():
    from paddle_tpu.analysis.collectives import parse_shape_bytes

    assert parse_shape_bytes("f32[8,128]{1,0}") == 8 * 128 * 4
    assert parse_shape_bytes("(bf16[4,4], f32[2])") == 4 * 4 * 2 + 2 * 4
    assert parse_shape_bytes("pred[]") == 1


def test_census_known_clean_single_device():
    report = analysis.audit(lambda a, b: jnp.dot(a, b),
                            jnp.ones((4, 4)), jnp.ones((4, 4)))
    assert report.total_collectives == 0


# ----------------------------------------------------------------- remat

def test_remat_pass_flags_incompatible_reshard():
    """Known-bad: a mid-graph sharding flip between transposed device
    orders forces GSPMD into replicate-then-repartition."""
    mesh = _mesh((4, 2), ("sharding", "mp"))
    v = jax.device_put(jnp.zeros((64, 64)),
                       NamedSharding(mesh, P(None, "mp")))

    def bad(a):
        b = jax.lax.with_sharding_constraint(
            jnp.sin(a), NamedSharding(mesh, P("sharding", None)))
        return jnp.cos(b)

    report = analysis.audit(jax.jit(bad), v)
    assert len(report.remat_events) >= 1
    ev = report.remat_events[0]
    assert ev.from_sharding and ev.to_sharding
    with pytest.raises(analysis.BudgetViolation, match="remat"):
        analysis.check_budget(jax.jit(bad),
                              analysis.Budget(max_remat=0), v)


def test_remat_pass_clean_on_consistent_layout():
    mesh = _mesh((4, 2), ("sharding", "mp"))
    v = jax.device_put(jnp.zeros((64, 64)),
                       NamedSharding(mesh, P(None, "mp")))

    def clean(a):
        return jnp.cos(jnp.sin(a))

    report = analysis.check_budget(
        jax.jit(clean), analysis.Budget(max_remat=0), v)
    assert report.remat_events == []


# ----------------------------------------------------------------- dtype

def test_dtype_pass_flags_deliberate_f32_upcast():
    """Known-bad: bf16 operands promoted to f32 before the matmul —
    the exact mistake that silently halves MXU rate."""
    def bad(w, x):
        return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))

    w = jnp.zeros((4, 4), jnp.bfloat16)
    x = jnp.zeros((2, 4), jnp.bfloat16)
    report = analysis.audit(bad, w, x)
    assert len(report.dtype.f32_compute) == 1
    assert report.dtype.f32_compute[0].primitive == "dot_general"
    assert report.dtype.upcasts == 2
    with pytest.raises(analysis.BudgetViolation, match="f32"):
        analysis.check_budget(
            bad, analysis.Budget(max_f32_matmuls=0), w, x)


def test_dtype_pass_clean_on_bf16_matmul():
    def clean(w, x):
        y = jnp.dot(x, w)          # stays bf16
        return y.sum(dtype=jnp.float32)  # f32 REDUCTION is fine

    w = jnp.zeros((4, 4), jnp.bfloat16)
    x = jnp.zeros((2, 4), jnp.bfloat16)
    report = analysis.check_budget(
        clean, analysis.Budget(max_f32_matmuls=0), w, x)
    assert report.dtype.f32_compute == []


def test_dtype_pass_sees_through_scan():
    """Taint must follow bf16 values into sub-jaxprs (scan bodies are
    where decode-loop upcasts hide)."""
    def bad(w, xs):
        def body(c, x):
            y = jnp.dot(x.astype(jnp.float32),
                        w.astype(jnp.float32))
            return c + y.sum(), y
        return jax.lax.scan(body, jnp.float32(0), xs)

    w = jnp.zeros((4, 4), jnp.bfloat16)
    xs = jnp.zeros((3, 2, 4), jnp.bfloat16)
    report = analysis.audit(bad, w, xs)
    assert any(ev.path for ev in report.dtype.f32_compute), \
        report.dtype.f32_compute


# -------------------------------------------------------------- donation

def test_donation_pass_flags_undonated_train_state():
    """Known-bad: an update step whose state rides through undonated —
    XLA must double-buffer the params."""
    def update(p, g):
        return p - 0.1 * g

    p = jnp.zeros((128, 128))
    g = jnp.ones((128, 128))
    bad = jax.jit(update)                       # nothing donated
    good = jax.jit(update, donate_argnums=(0,))

    rep_bad = analysis.audit(bad, p, g)
    assert rep_bad.donation.donated_count == 0
    rep_good = analysis.audit(good, p, g)
    assert rep_good.donation.args[0].donated
    assert not rep_good.donation.args[1].donated


def test_donation_budget_on_jitted_train_step():
    """JittedTrainStep declares its donatable leaves; require_donated
    passes with donate=True and fails with donate=False."""
    from paddle_tpu.jit.train import JittedTrainStep
    import paddle_tpu.nn as nn

    def build(donate):
        paddle.seed(0)
        model = nn.Linear(16, 16)
        opt = paddle.optimizer.AdamW(1e-3,
                                     parameters=model.parameters())
        mse = nn.MSELoss()
        return JittedTrainStep(model, lambda o, y: mse(o, y), opt,
                               donate=donate)

    x = paddle.to_tensor(np.ones((4, 16), np.float32))
    good = build(donate=True)
    report = analysis.check_budget(
        good, analysis.Budget(require_donated=True, max_remat=0), x, x)
    assert report.donation.undonated() == []

    bad = build(donate=False)
    with pytest.raises(analysis.BudgetViolation, match="donat"):
        analysis.check_budget(
            bad, analysis.Budget(require_donated=True), x, x)


# ---------------------------------------------------------------- budget

def test_budget_rejects_unknown_fields():
    with pytest.raises(TypeError, match="unknown budget field"):
        analysis.Budget(max_all_gather=3)  # typo'd name


def test_budget_violations_aggregate():
    mesh = _mesh((8,), ("dp",))

    def step(p, x):
        g = jnp.dot(x, p)
        return p - 0.1 * jnp.dot(x.T, g)

    p = jax.device_put(jnp.zeros((64, 64)), NamedSharding(mesh, P()))
    x = jax.device_put(jnp.ones((8, 64)),
                       NamedSharding(mesh, P("dp")))
    jitted = jax.jit(step)
    with pytest.raises(analysis.BudgetViolation) as ei:
        analysis.check_budget(
            jitted,
            analysis.Budget(name="toy", max_all_reduces=0,
                            max_collective_bytes=0), p, x)
    msg = str(ei.value)
    assert "all-reduce count" in msg and "collective bytes" in msg
    assert ei.value.report.total_collectives == 1


# --------------------------------------------------- real-recipe budgets

def test_recipe_budget_tp_zero_fused_lce():
    """The round-5 hybrid recipe compiles within its declared budget:
    0 involuntary remats, the stage-2 reduce-scatter decision present,
    every param/state/buffer leaf donated, bounded all-gather count."""
    report = analysis.run_recipe("llama_tp_zero_fused_lce")
    assert report.remat_events == []
    assert report.collectives["all-gather"].count > 0  # TP really talks
    assert report.donation.undonated() == []


def test_recipe_budget_decode_greedy():
    """The single-chip bf16 serving loop: no collectives (any would be
    an accidental mesh dependency) and the bf16 graph stays bf16."""
    report = analysis.run_recipe("llama_decode_greedy")
    assert report.total_collectives == 0
    assert report.dtype is not None
    assert report.dtype.f32_compute == []


def test_audit_summary_is_printable():
    report = analysis.audit(lambda a: a * 2, jnp.ones((4,)))
    text = report.summary()
    assert "collectives" in text and "remat" in text
