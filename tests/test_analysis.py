"""paddle_tpu.analysis — graph auditor + budget mechanism + golden
fingerprint drift gate.

Each IR pass gets a KNOWN-BAD function it must flag and a KNOWN-CLEAN
function it must not, plus the registered real-recipe budgets AND
their checked-in golden fingerprints (tests/goldens/<recipe>.json)
which must hold on the current code — these are the machine-checked
"did not regress the compiled graph" guarantees every future perf PR
inherits. The serving recipes' budget+fingerprint gates live in
tests/test_serving.py next to the engine tests; the CLI (--check /
--fingerprint, success and failure paths) is exercised end-to-end
here."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.parallel import mesh as mesh_state


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def _mesh(shape, axes):
    return Mesh(np.array(jax.devices()).reshape(*shape), axes)


# ---------------------------------------------------------------- census

def test_collective_census_counts_and_bytes():
    mesh = _mesh((8,), ("dp",))

    def step(p, x):
        g = jnp.dot(x, p)
        return p - 0.1 * jnp.dot(x.T, g)

    p = jax.device_put(jnp.zeros((64, 64)), NamedSharding(mesh, P()))
    x = jax.device_put(jnp.ones((8, 64)),
                       NamedSharding(mesh, P("dp")))
    report = analysis.audit(jax.jit(step), p, x)
    # dp grads reduce over the mesh: exactly one all-reduce of the
    # (64, 64) f32 gradient
    st = report.collectives["all-reduce"]
    assert st.count == 1
    assert st.bytes == 64 * 64 * 4
    assert report.collectives["all-gather"].count == 0
    assert report.total_collectives == 1


def test_parse_shape_bytes_tuple_and_scalars():
    from paddle_tpu.analysis.collectives import parse_shape_bytes

    assert parse_shape_bytes("f32[8,128]{1,0}") == 8 * 128 * 4
    assert parse_shape_bytes("(bf16[4,4], f32[2])") == 4 * 4 * 2 + 2 * 4
    assert parse_shape_bytes("pred[]") == 1


def test_census_known_clean_single_device():
    report = analysis.audit(lambda a, b: jnp.dot(a, b),
                            jnp.ones((4, 4)), jnp.ones((4, 4)))
    assert report.total_collectives == 0


# ----------------------------------------------------------------- remat

def test_remat_pass_flags_incompatible_reshard():
    """Known-bad: a mid-graph sharding flip between transposed device
    orders forces GSPMD into replicate-then-repartition."""
    mesh = _mesh((4, 2), ("sharding", "mp"))
    v = jax.device_put(jnp.zeros((64, 64)),
                       NamedSharding(mesh, P(None, "mp")))

    def bad(a):
        b = jax.lax.with_sharding_constraint(
            jnp.sin(a), NamedSharding(mesh, P("sharding", None)))
        return jnp.cos(b)

    report = analysis.audit(jax.jit(bad), v)
    assert len(report.remat_events) >= 1
    ev = report.remat_events[0]
    assert ev.from_sharding and ev.to_sharding
    with pytest.raises(analysis.BudgetViolation, match="remat"):
        analysis.check_budget(jax.jit(bad),
                              analysis.Budget(max_remat=0), v)


def test_remat_pass_clean_on_consistent_layout():
    mesh = _mesh((4, 2), ("sharding", "mp"))
    v = jax.device_put(jnp.zeros((64, 64)),
                       NamedSharding(mesh, P(None, "mp")))

    def clean(a):
        return jnp.cos(jnp.sin(a))

    report = analysis.check_budget(
        jax.jit(clean), analysis.Budget(max_remat=0), v)
    assert report.remat_events == []


# ----------------------------------------------------------------- dtype

def test_dtype_pass_flags_deliberate_f32_upcast():
    """Known-bad: bf16 operands promoted to f32 before the matmul —
    the exact mistake that silently halves MXU rate."""
    def bad(w, x):
        return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))

    w = jnp.zeros((4, 4), jnp.bfloat16)
    x = jnp.zeros((2, 4), jnp.bfloat16)
    report = analysis.audit(bad, w, x)
    assert len(report.dtype.f32_compute) == 1
    assert report.dtype.f32_compute[0].primitive == "dot_general"
    assert report.dtype.upcasts == 2
    with pytest.raises(analysis.BudgetViolation, match="f32"):
        analysis.check_budget(
            bad, analysis.Budget(max_f32_matmuls=0), w, x)


def test_dtype_pass_clean_on_bf16_matmul():
    def clean(w, x):
        y = jnp.dot(x, w)          # stays bf16
        return y.sum(dtype=jnp.float32)  # f32 REDUCTION is fine

    w = jnp.zeros((4, 4), jnp.bfloat16)
    x = jnp.zeros((2, 4), jnp.bfloat16)
    report = analysis.check_budget(
        clean, analysis.Budget(max_f32_matmuls=0), w, x)
    assert report.dtype.f32_compute == []


def test_dtype_pass_sees_through_scan():
    """Taint must follow bf16 values into sub-jaxprs (scan bodies are
    where decode-loop upcasts hide)."""
    def bad(w, xs):
        def body(c, x):
            y = jnp.dot(x.astype(jnp.float32),
                        w.astype(jnp.float32))
            return c + y.sum(), y
        return jax.lax.scan(body, jnp.float32(0), xs)

    w = jnp.zeros((4, 4), jnp.bfloat16)
    xs = jnp.zeros((3, 2, 4), jnp.bfloat16)
    report = analysis.audit(bad, w, xs)
    assert any(ev.path for ev in report.dtype.f32_compute), \
        report.dtype.f32_compute


# -------------------------------------------------------------- donation

def test_donation_pass_flags_undonated_train_state():
    """Known-bad: an update step whose state rides through undonated —
    XLA must double-buffer the params."""
    def update(p, g):
        return p - 0.1 * g

    p = jnp.zeros((128, 128))
    g = jnp.ones((128, 128))
    bad = jax.jit(update)                       # nothing donated
    good = jax.jit(update, donate_argnums=(0,))

    rep_bad = analysis.audit(bad, p, g)
    assert rep_bad.donation.donated_count == 0
    rep_good = analysis.audit(good, p, g)
    assert rep_good.donation.args[0].donated
    assert not rep_good.donation.args[1].donated


def test_donation_budget_on_jitted_train_step():
    """JittedTrainStep declares its donatable leaves; require_donated
    passes with donate=True and fails with donate=False."""
    from paddle_tpu.jit.train import JittedTrainStep
    import paddle_tpu.nn as nn

    def build(donate):
        paddle.seed(0)
        model = nn.Linear(16, 16)
        opt = paddle.optimizer.AdamW(1e-3,
                                     parameters=model.parameters())
        mse = nn.MSELoss()
        return JittedTrainStep(model, lambda o, y: mse(o, y), opt,
                               donate=donate)

    x = paddle.to_tensor(np.ones((4, 16), np.float32))
    good = build(donate=True)
    report = analysis.check_budget(
        good, analysis.Budget(require_donated=True, max_remat=0), x, x)
    assert report.donation.undonated() == []

    bad = build(donate=False)
    with pytest.raises(analysis.BudgetViolation, match="donat"):
        analysis.check_budget(
            bad, analysis.Budget(require_donated=True), x, x)


# ---------------------------------------------------------------- budget

def test_budget_rejects_unknown_fields():
    with pytest.raises(TypeError, match="unknown budget field"):
        analysis.Budget(max_all_gather=3)  # typo'd name


def test_budget_violations_aggregate():
    mesh = _mesh((8,), ("dp",))

    def step(p, x):
        g = jnp.dot(x, p)
        return p - 0.1 * jnp.dot(x.T, g)

    p = jax.device_put(jnp.zeros((64, 64)), NamedSharding(mesh, P()))
    x = jax.device_put(jnp.ones((8, 64)),
                       NamedSharding(mesh, P("dp")))
    jitted = jax.jit(step)
    with pytest.raises(analysis.BudgetViolation) as ei:
        analysis.check_budget(
            jitted,
            analysis.Budget(name="toy", max_all_reduces=0,
                            max_collective_bytes=0), p, x)
    msg = str(ei.value)
    assert "all-reduce count" in msg and "collective bytes" in msg
    assert ei.value.report.total_collectives == 1


# --------------------------------------------------- real-recipe budgets

def test_recipe_budget_tp_zero_fused_lce():
    """The round-5 hybrid recipe compiles within its declared budget:
    0 involuntary remats, the stage-2 reduce-scatter decision present,
    every param/state/buffer leaf donated, bounded all-gather count,
    peak live bytes capped, no replicated weight leaves — and the full
    fingerprint matches the checked-in TP2 x ZeRO golden (same report,
    no extra compile)."""
    report = analysis.run_recipe("llama_tp_zero_fused_lce")
    assert report.remat_events == []
    assert report.collectives["all-gather"].count > 0  # TP really talks
    assert report.donation.undonated() == []
    # the sharding pass sees the layout: params + moments carry a real
    # axis, only the 1-D norm scales (256 B) replicate
    assert report.sharding.sharded_param_count >= 40
    assert report.sharding.max_replicated_param_bytes <= 4096
    analysis.check_recipe_fingerprint("llama_tp_zero_fused_lce", report)


def test_recipe_budget_decode_greedy():
    """The single-chip bf16 serving loop: no collectives (any would be
    an accidental mesh dependency), the bf16 graph stays bf16, temp and
    output allocations stay tiny — and the fingerprint matches its
    golden."""
    report = analysis.run_recipe("llama_decode_greedy")
    assert report.total_collectives == 0
    assert report.dtype is not None
    assert report.dtype.f32_compute == []
    assert report.memory.temp_bytes is not None
    analysis.check_recipe_fingerprint("llama_decode_greedy", report)


def test_audit_summary_is_printable():
    report = analysis.audit(lambda a: a * 2, jnp.ones((4,)))
    text = report.summary()
    assert "collectives" in text and "remat" in text
    assert "memory" in text and "sharding" in text


def test_audit_summary_is_dict_order_independent():
    """The summary text must not depend on dict insertion order —
    fingerprint diffs and capfd assertions read it verbatim."""
    report = analysis.audit(lambda a: a * 2, jnp.ones((4,)))
    base = report.summary()
    report.collectives = dict(
        sorted(report.collectives.items(), reverse=True))
    assert report.summary() == base


# ---------------------------------------------------------------- memory

def test_liveness_walk_donation_savings():
    """A donated input that dies early shrinks peak live bytes; an
    undonated one is held for the whole program."""
    from paddle_tpu.analysis import jaxpr_liveness

    def f(p, g):
        a = p * 2.0          # p's last use: dies here if donated
        b = a + g
        c = b * g
        return c

    args = (jnp.ones((256, 256)), jnp.ones((256, 256)))
    closed = jax.make_jaxpr(f)(*args)
    donated = jaxpr_liveness(closed, donated=(0,))
    held = jaxpr_liveness(closed, donated=())
    assert donated.donation_savings_bytes > 0
    assert donated.peak_live_bytes < held.peak_live_bytes
    assert held.donation_savings_bytes == 0
    assert donated.largest_buffer_bytes == 256 * 256 * 4
    # the walk sees through the single pjit eqn jax.jit wraps around
    closed_jit = jax.make_jaxpr(jax.jit(f))(*args)
    assert jaxpr_liveness(closed_jit, donated=(0,)).peak_live_bytes \
        == donated.peak_live_bytes


def test_memory_budget_caps_enforced():
    """max_temp_bytes / max_peak_live_bytes / max_output_bytes trip on
    a known-fat program and pass with honest headroom."""
    def fat(a):
        return jnp.dot(a, a)

    a = jnp.ones((64, 64))
    with pytest.raises(analysis.BudgetViolation) as ei:
        analysis.check_budget(
            fat, analysis.Budget(name="toy-mem", max_temp_bytes=0,
                                 max_peak_live_bytes=1,
                                 max_output_bytes=1), a)
    msg = str(ei.value)
    assert "peak live bytes" in msg and "output bytes" in msg
    report = analysis.check_budget(
        fat, analysis.Budget(max_peak_live_bytes=10 * 64 * 64 * 4), a)
    assert report.memory.peak_live_bytes >= 2 * 64 * 64 * 4
    assert report.memory.compiler is not None  # CPU backend reports


# -------------------------------------------------------------- sharding

def test_sharding_attr_classification():
    """_classify returns (replicated, unknown): every recognized syntax
    parses with unknown=False; unrecognized syntax is classified
    replicated (strict fallback) but COUNTED unknown so a report can
    tell a parser gap from an actually-replicated leaf."""
    from paddle_tpu.analysis.sharding import _classify

    assert _classify("") == (True, False)
    assert _classify(None) == (True, False)
    assert _classify("{replicated}") == (True, False)
    assert _classify("{maximal device=0}") == (True, False)
    assert _classify(
        "{devices=[1,1,8]<=[8] last_tile_dim_replicate}") == (True, False)
    assert _classify("{devices=[2,4]<=[8]}") == (False, False)
    assert _classify(
        "{devices=[2,1,4]<=[8] last_tile_dim_replicate}") == (False, False)
    # unknown syntax: strict (replicated) AND counted
    assert _classify("{v2_tuple_shardings_from_the_future}") == (True, True)


def test_sharding_unknown_syntax_counted_in_report():
    """An entry arg carrying unparseable sharding syntax lands in the
    report as replicated (the audit stays strict) with unknown_count
    nonzero — and summary_dict only GROWS the unknown_shardings key in
    that case, so every existing golden (all-parsed) stays
    byte-identical."""
    from paddle_tpu.analysis.sharding import audit_sharding

    hlo = (
        'func.func public @main('
        '%arg0: tensor<4x4xf32> {mhlo.sharding = "{devices=[2,1]<=[2]}"}, '
        '%arg1: tensor<4x4xf32> {mhlo.sharding = "{weird_future_repr}"}, '
        '%arg2: tensor<4xf32>) -> tensor<4xf32> {'
    )
    rep = audit_sharding(hlo)
    assert rep.sharded_count == 1
    assert rep.unknown_count == 1
    unk = [a for a in rep.args if a.unknown]
    assert len(unk) == 1 and unk[0].replicated  # strict fallback holds
    assert "unknown syntax" in repr(unk[0])
    assert rep.summary_dict()["unknown_shardings"] == 1
    # the common fully-parsed case: key absent -> goldens untouched
    clean = audit_sharding(hlo.replace("{weird_future_repr}",
                                       "{replicated}"))
    assert clean.unknown_count == 0
    assert "unknown_shardings" not in clean.summary_dict()


def test_sharding_pass_flags_replicated_param():
    """Known-bad: a large param left replicated over a real mesh while
    the mesh is in play; max_replicated_param_bytes catches it, and the
    sharded variant passes the same budget."""
    mesh = _mesh((8,), ("dp",))

    class _Declared:
        """jitted target + n_donatable (the param is arg 0)."""

        def __init__(self, jitted):
            self._jitted = jitted
            self.n_donatable = 1
            self.__name__ = "declared_step"

        def lower(self, *args):
            return self._jitted.lower(*args)

    def step(p, x):
        return p, (x @ p).sum()

    p_rep = jax.device_put(jnp.zeros((128, 128)),
                           NamedSharding(mesh, P()))
    p_shard = jax.device_put(jnp.zeros((128, 128)),
                             NamedSharding(mesh, P("dp", None)))
    x = jax.device_put(jnp.ones((8, 128)), NamedSharding(mesh, P("dp")))
    budget = analysis.Budget(name="no-fat-replicas",
                             max_replicated_param_bytes=1024,
                             min_sharded_params=1)
    target = _Declared(jax.jit(step, donate_argnums=(0,)))
    with pytest.raises(analysis.BudgetViolation) as ei:
        analysis.check_budget(target, budget, p_rep, x)
    assert "replicated donatable leaves" in str(ei.value)
    report = analysis.check_budget(target, budget, p_shard, x)
    assert report.sharding.sharded_param_count == 1


# ----------------------------------------------------------- fingerprint

def test_fingerprint_mutation_produces_readable_diff():
    """Acceptance: dropping donate_argnums in a test-local copy of a
    step drifts the fingerprint with a field-level, human-readable
    diff."""
    def update(p, g):
        return p - 0.1 * g

    p, g = jnp.zeros((64, 64)), jnp.ones((64, 64))
    golden_report = analysis.audit(
        jax.jit(update, donate_argnums=(0,)), p, g)
    golden = analysis.fingerprint_report(golden_report, name="toy")
    mutated_report = analysis.audit(jax.jit(update), p, g)  # donation lost
    mutated = analysis.fingerprint_report(mutated_report, name="toy")
    diff = analysis.compare_fingerprint(golden, mutated)
    assert diff, "dropped donation must drift the fingerprint"
    text = "\n".join(diff)
    assert "donation.donated: golden 1 != current 0 (-1)" in text
    # identical audits do NOT drift
    assert analysis.compare_fingerprint(golden, golden) == []


def test_fingerprint_golden_roundtrip(tmp_path):
    report = analysis.audit(lambda a: a * 2, jnp.ones((64,)))
    fp = analysis.fingerprint_report(report, name="roundtrip")
    analysis.save_golden(fp, "roundtrip", goldens_dir=str(tmp_path))
    assert analysis.load_golden("roundtrip",
                                goldens_dir=str(tmp_path)) == fp
    assert analysis.check_recipe_fingerprint(
        "roundtrip", report, goldens_dir=str(tmp_path)) == fp
    with pytest.raises(analysis.FingerprintMismatch, match="no golden"):
        analysis.check_recipe_fingerprint(
            "never_saved", report, goldens_dir=str(tmp_path))


# ------------------------------------------------- CLI (serving recipes)

def test_cli_check_and_fingerprint_serving_recipe(capsys):
    """`python -m paddle_tpu.analysis --recipe serving_decode_step
    --check --fingerprint` end-to-end: budget enforced and golden
    compared in one invocation, exit 0, readable output."""
    from paddle_tpu.analysis.__main__ import main

    rc = main(["--recipe", "serving_decode_step", "--check",
               "--fingerprint"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "budget [serving decode quantum" in out and "OK" in out
    assert "fingerprint: OK" in out
    assert "memory (compiler):" in out and "sharding:" in out


def test_cli_failure_paths_print_readable_diff(tmp_path, capsys,
                                               monkeypatch):
    """Injected violation + doctored golden: the CLI exits 1 and prints
    BOTH the budget violation and the per-field fingerprint diff."""
    from paddle_tpu.analysis import fingerprint as fpm
    from paddle_tpu.analysis import recipes
    from paddle_tpu.analysis.__main__ import main

    orig = recipes.RECIPES["serving_decode_step"]

    def tightened():
        recipe = orig()
        recipe.budget.max_temp_bytes = 1  # impossible: injected violation
        return recipe

    monkeypatch.setitem(recipes.RECIPES, "serving_decode_step",
                        tightened)
    golden = fpm.load_golden("serving_decode_step")
    assert golden is not None, "checked-in golden missing"
    golden["involuntary_remat"] = 7  # doctored: force a drift
    fpm.save_golden(golden, "serving_decode_step",
                    goldens_dir=str(tmp_path))

    rc = main(["--recipe", "serving_decode_step", "--check",
               "--fingerprint", "--goldens-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "VIOLATED" in out
    assert "compiled temp bytes" in out  # the injected budget breach
    assert "fingerprint: drift" in out
    assert "involuntary_remat: golden 7 != current 0 (-7)" in out
