"""Fault-tolerant serving (ISSUE 13): deterministic fault injection,
watchdog + retry/quarantine, the degradation ladders, and
crash-recoverable snapshots.

The correctness bar everywhere is the serving engine's own oracle:
greedy rows are batch-independent, so no matter which faults fire —
transient raises retried with backoff, allocation failures skipping a
step, a poison request bisect-quarantined out of the batch, the spec
round auto-disabled, a corrupted cached subtree dropped, the pool
allocator rebuilt from live tables, or the whole engine snapshotted
and restored into a fresh process — every surviving stream must stay
BIT-EXACT vs the fault-free run, and the pool must come back to its
pristine residency (the engine scratch block, plus the prefix index's
cached blocks when caching is on).

The golden gate for degraded modes: a spec engine that trips the
spec-disable ladder re-jits the PLAIN quantum family — audited here
against the checked-in ``serving_decode_step`` fingerprint
byte-for-byte (``max_context=254`` keeps the table width identical to
the plain recipe's 256 once the gamma margin is gone), so degrading
never introduces a new compiled program.

The seeded chaos soak (paddle_tpu/serving/soak.py) interleaves
faults x spec x preemption x COW prefix sharing: a bounded smoke runs
tier-1, the 200-round acceptance soak is ``slow``-marked (also driven
by scripts/soak.py and the ``python -m paddle_tpu.obs check`` gate).
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp.generation import generate_on_device
from paddle_tpu.serving import (
    FaultInjector, FaultSpec, InjectedFault, QuantumWatchdog,
    ResiliencePolicy, ServingEngine,
)
from paddle_tpu.serving.soak import run_soak


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


@pytest.fixture(scope="module")
def tiny_draft():
    paddle.seed(11)
    draft = LlamaForCausalLM(
        LlamaConfig.tiny(tensor_parallel=False, num_hidden_layers=1))
    draft.eval()
    return draft


def _nosleep(_s):
    return None


def _policy(**kw):
    kw.setdefault("sleep", _nosleep)
    return ResiliencePolicy(**kw)


def _oracle_row(model, prompt, max_new):
    out = generate_on_device(model, paddle.to_tensor(prompt[None, :]),
                             max_new_tokens=max_new)
    return np.asarray(out._value)[0]


@pytest.fixture(scope="module")
def workload(tiny_model):
    """Three ragged greedy requests + their sequential oracle rows —
    shared by every fault scenario so the oracle compiles once (three
    lengths keep the tier-1 eager mixed-prefill bill bounded; the
    chaos soaks cover wider raggedness)."""
    cfg, model = tiny_model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, n).astype(np.int32)
               for n in (5, 9, 3)]
    max_new = [6, 4, 8]
    wants = [_oracle_row(model, p, mn)
             for p, mn in zip(prompts, max_new)]
    return prompts, max_new, wants


def _submit_all(engine, prompts, max_new):
    return [engine.submit(p, max_new_tokens=mn)
            for p, mn in zip(prompts, max_new)]


# ------------------------------------------------ injector units
def test_fault_injector_determinism_and_validation():
    """Same seed + plan + call sequence -> identical journals (the
    replay contract); a poisoned active row always raises; bad
    site/kind rejected at construction; a default injector is disarmed
    and every hook is a no-op."""
    def drive(seed):
        inj = FaultInjector(
            plan=[FaultSpec("decode", "raise", p=0.4),
                  FaultSpec("alloc", "alloc_fail", p=0.3, times=2)],
            seed=seed)
        for i in range(40):
            try:
                inj.before_dispatch("decode", [f"r{i % 3}"])
            except InjectedFault as e:
                assert e.site == "decode" and e.kind == "raise"
            try:
                inj.on_alloc(None)
            except InjectedFault as e:
                assert e.kind == "alloc_fail"
        return inj
    a, b = drive(7), drive(7)
    assert a.journal and a.journal == b.journal
    assert a.injected_total == b.injected_total
    assert drive(8).journal != a.journal
    # the alloc spec honored its times=2 bound
    assert sum(1 for j in a.journal if j["site"] == "alloc") == 2

    inj = FaultInjector(plan=[FaultSpec("decode", "raise", p=0.0)])
    inj.poison("bad")
    assert inj.armed and "bad" in inj.poisoned
    with pytest.raises(InjectedFault) as ei:
        inj.before_dispatch("decode", ["ok", "bad"])
    assert ei.value.poison == "bad"
    inj.cure("bad")
    inj.before_dispatch("decode", ["ok", "bad"])  # cured: no raise

    with pytest.raises(ValueError, match="site"):
        FaultSpec("gpu", "raise")
    with pytest.raises(ValueError, match="kind"):
        FaultSpec("decode", "explode")
    off = FaultInjector()
    assert not off.armed
    off.before_dispatch("decode", ["r0"])
    off.on_alloc(None)
    assert off.journal == [] and off.injected_total == 0


def test_watchdog_calibration_unit():
    """deadline(kind) is None until min_samples, then
    max(p99 * margin, floor); check() tests against the deadline that
    held BEFORE the new observation; trips count per kind."""
    wd = QuantumWatchdog(_policy(min_samples=4, min_deadline_s=0.001,
                                 deadline_margin=2.0))
    for _ in range(3):
        assert wd.deadline("decode") is None
        assert not wd.check("decode", 0.010)
    assert not wd.check("decode", 0.010)        # 4th sample arms it
    limit = wd.deadline("decode")
    assert limit is not None and 0.001 < limit < 0.1
    assert wd.check("decode", 10.0)             # gross overrun trips
    assert not wd.check("mixed", 10.0)          # other kinds still cold
    assert wd.trips_total == 1 and wd.trips == {"decode": 1}
    assert wd.stats()["trips_total"] == 1
    pol = _policy(backoff_base_s=0.01, backoff_mult=2.0)
    assert pol.backoff_s(0) == pytest.approx(0.01)
    assert pol.backoff_s(3) == pytest.approx(0.08)
    with pytest.raises(ValueError):
        ResiliencePolicy(max_retries=-1)
    with pytest.raises(ValueError):
        ResiliencePolicy(spec_fault_threshold=0)


# ------------------------------------------------ engine scenarios
@pytest.mark.slow
def test_disarmed_injector_and_policy_are_inert(tiny_model, workload):
    """The parity claim the goldens rest on: resilience tier ON with a
    DISARMED injector changes nothing — streams bit-exact vs the
    sequential oracle, zero journal entries, zero retries/skips, pool
    pristine.

    Slow-tiered for the tier-1 wall-clock budget: the claim stays
    tier-1 three ways — every engine now constructs a disarmed
    injector, so test_serving's fingerprint/golden tests exercise the
    seams on every run; the armed runs below are bit-exact *through*
    recovery (strictly stronger than disarmed parity); and
    test_fault_injector_determinism asserts the disarmed injector is a
    literal no-op at the unit level."""
    cfg, model = tiny_model
    prompts, max_new, wants = workload
    eng = ServingEngine(model, num_slots=3, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        faults=FaultInjector(seed=0),
                        resilience=_policy())
    reqs = _submit_all(eng, prompts, max_new)
    eng.run()
    for req, want in zip(reqs, wants):
        np.testing.assert_array_equal(eng.output_tokens(req), want)
    rep = eng.resilience_report()
    assert rep["retries_total"] == 0 and rep["step_skips"] == 0
    assert rep["quarantined"] == [] and not rep["spec_disabled"]
    assert rep["faults"]["injected_total"] == 0
    assert eng.pool.fragmentation_stats()["blocks_in_use"] == 1


def test_transient_faults_retry_skip_and_rebuild(tiny_model, workload):
    """One run, three containment paths: bounded transient decode
    raises are retried with backoff (bit-exact afterwards), an
    allocation failure skips the step and the next step retries
    naturally, and a seeded pool-accounting drift (a mapped block's
    refcount entry deleted mid-run) triggers the rebuild ladder —
    allocator reconstructed from live tables, serving continues, and
    every stream still matches the oracle."""
    cfg, model = tiny_model
    prompts, max_new, wants = workload
    slept = []
    inj = FaultInjector(plan=[FaultSpec("decode", "raise", times=2),
                              FaultSpec("alloc", "alloc_fail", times=1)],
                        seed=3)
    eng = ServingEngine(model, num_slots=3, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        faults=inj,
                        resilience=_policy(max_retries=3,
                                           sleep=slept.append))
    reqs = _submit_all(eng, prompts, max_new)
    # let prefill+early decode land, then corrupt the allocator books
    for _ in range(4):
        eng.step()
    mapped = [b for s, t in eng.pool._tables.items()
              if s != "__scratch__" for b in t]
    assert mapped
    del eng.pool._refcounts[mapped[0]]
    eng.run()
    for req, want in zip(reqs, wants):
        np.testing.assert_array_equal(eng.output_tokens(req), want)
    rep = eng.resilience_report()
    assert rep["retries_total"] == 2
    # exponential schedule: base 0.01, then x2 within one dispatch
    assert slept == [pytest.approx(0.01), pytest.approx(0.02)]
    assert rep["step_skips"] >= 1          # the alloc_fail
    assert rep["pool_rebuilds"] == 1
    assert rep["faults"]["injected_total"] >= 3
    assert eng.pool.fragmentation_stats()["blocks_in_use"] == 1
    reg = eng.obs.registry
    assert reg.get("serving_quantum_retries_total").value(
        kind="decode") == 2
    assert reg.get("serving_faults_injected_total").value(
        site="decode", kind="raise") == 2
    assert reg.get("serving_faults_injected_total").value(
        site="alloc", kind="alloc_fail") == 1
    assert reg.get("serving_degraded_mode").value(
        mode="pool_rebuild") == 1.0


def test_poison_bisect_quarantine(tiny_model, workload):
    """A poisoned decoding row is isolated by batch bisect (real
    probe dispatches, no exception introspection), finished with
    ``finish_reason="error"``, and everyone else's stream is
    bit-exact; the quarantined request's blocks are back in the free
    list at drain."""
    cfg, model = tiny_model
    prompts, max_new, wants = workload
    inj = FaultInjector(seed=0)
    eng = ServingEngine(model, num_slots=3, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        faults=inj, resilience=_policy())
    reqs = _submit_all(eng, prompts, max_new)
    # poison once several rows are decoding, so the bisect has a batch
    while len(reqs[1].tokens) < 1:
        eng.step()
    inj.poison(reqs[1].req_id)
    eng.run()
    assert reqs[1].finished and reqs[1].finish_reason == "error"
    for i, (req, want) in enumerate(zip(reqs, wants)):
        if i == 1:
            continue
        assert req.finish_reason == "length"
        np.testing.assert_array_equal(eng.output_tokens(req), want)
    rep = eng.resilience_report()
    assert rep["quarantined"] == [str(reqs[1].req_id)]
    assert not inj.poisoned                 # cured at quarantine
    assert eng.pool.fragmentation_stats()["blocks_in_use"] == 1
    assert eng.obs.registry.get(
        "serving_quarantines_total").value(kind="poison") == 1


def test_watchdog_trips_on_slow_quantum(tiny_model):
    """Deterministic engine-level trip: every decode dispatch is
    stalled past a floored deadline by a ``slow`` fault (real sleep),
    the first dispatch seeds the histogram, every later one trips —
    detection-only, so the stream is untouched."""
    cfg, model = tiny_model
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, cfg.vocab_size, 5).astype(np.int32)
    inj = FaultInjector(plan=[FaultSpec("decode", "slow",
                                        sleep_s=0.12)], seed=0)
    eng = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=8, decode_quantum=1,
                        faults=inj,
                        resilience=_policy(min_samples=1,
                                           min_deadline_s=0.05,
                                           deadline_margin=0.01))
    want = _oracle_row(model, prompt, 5)
    req = eng.submit(prompt, max_new_tokens=5)
    eng.run()
    np.testing.assert_array_equal(eng.output_tokens(req), want)
    wd = eng.resilience_report()["watchdog"]
    assert wd["trips"].get("decode", 0) >= 1
    assert eng.obs.registry.get(
        "serving_watchdog_trips_total").value(kind="decode") >= 1


def test_spec_disable_ladder_matches_plain_golden(tiny_model,
                                                 tiny_draft,
                                                 workload):
    """Ladder rung 1 + the degraded-mode golden gate: repeated
    spec-round faults one-way disable speculative decoding; in-flight
    streams continue bit-exact on the plain quantum, and the fallback's
    audited program matches the checked-in ``serving_decode_step``
    fingerprint BYTE-FOR-BYTE (max_context=254 => the gamma-free table
    width equals the plain recipe's 256-context width) — degrading
    compiles no new golden."""
    cfg, model = tiny_model
    prompts, max_new, wants = workload
    inj = FaultInjector(plan=[FaultSpec("spec_round", "raise",
                                        times=2)], seed=0)
    eng = ServingEngine(model, spec_draft=tiny_draft, spec_gamma=2,
                        num_slots=2, block_size=4, prefill_chunk=8,
                        decode_quantum=4, max_context=254,
                        faults=inj,
                        resilience=_policy(max_retries=0,
                                           spec_fault_threshold=2))
    reqs = _submit_all(eng, prompts[:2], max_new[:2])
    eng.run()
    assert eng._spec_disabled
    rep = eng.resilience_report()
    assert rep["spec_disabled"] and rep["spec_faults"] >= 2
    for req, want in zip(reqs, wants):
        np.testing.assert_array_equal(
            eng.output_tokens(req), want[:len(req.prompt)
                                         + len(req.tokens)])
        assert req.finish_reason == "length"
    tgt, args = eng.decode_step_target()
    report = analysis.audit(tgt, *args)
    analysis.check_recipe_fingerprint("serving_decode_step", report)
    assert eng.obs.registry.get("serving_degraded_mode").value(
        mode="spec_disabled") == 1.0
    assert eng.pool.fragmentation_stats()["blocks_in_use"] == 1
    assert eng.d_pool.fragmentation_stats()["blocks_in_use"] == 1


def test_prefix_bitflip_quarantines_subtree(tiny_model):
    """Ladder rung 2: a bit flipped in a CACHED-ONLY block is caught by
    the chain-hash verify at the next ``attach_prefix`` — the corrupted
    subtree is quarantined out of the index, the new request re-prefills
    cleanly, and every stream is bit-exact (corruption never reaches a
    live row)."""
    cfg, model = tiny_model
    rng = np.random.RandomState(9)
    prefix = rng.randint(1, cfg.vocab_size, 12).astype(np.int32)
    tails = [rng.randint(1, cfg.vocab_size, 4).astype(np.int32)
             for _ in range(5)]
    prompts = [np.concatenate([prefix, t]) for t in tails]
    wants = [_oracle_row(model, p, 4) for p in prompts]
    inj = FaultInjector(plan=[FaultSpec("kv", "bit_flip", times=1)],
                        seed=2)
    eng = ServingEngine(model, num_slots=2, block_size=4,
                        prefill_chunk=4, decode_quantum=2,
                        prefix_cache=True, faults=inj,
                        resilience=_policy())
    first = _submit_all(eng, prompts[:4], [4] * 4)
    eng.run()
    # the flip landed on a now cached-only block of the shared chain
    assert any("block" in j for j in inj.journal), inj.journal
    fifth = eng.submit(prompts[4], max_new_tokens=4)
    eng.run()
    rep = eng.resilience_report()
    assert rep["prefix_quarantines"] >= 1
    assert eng.pool.prefix_quarantines >= 1
    for req, want in zip(first + [fifth], wants):
        np.testing.assert_array_equal(eng.output_tokens(req), want)
    assert eng.obs.registry.get("serving_quarantines_total").value(
        kind="prefix") >= 1


def test_restore_rejects_foreign_payload(tiny_model):
    """The on-disk contract is tagged: restore refuses a payload that
    is not a serving_engine_snapshot (cheap unit — the full mid-flight
    round-trip below is slow-tiered)."""
    cfg, model = tiny_model
    with pytest.raises(ValueError, match="snapshot"):
        ServingEngine.restore({"kind": "nope"}, model)


@pytest.mark.slow
def test_snapshot_restore_resumes_bit_exact(tiny_model, workload):
    """Crash recovery: snapshot mid-flight (JSON round-trip — the
    on-disk contract), restore into a FRESH engine, and every stream
    completes bit-exact vs the uninterrupted oracle via
    recompute-on-resume; recomputed tokens are not re-emitted.

    Slow-tiered for the tier-1 wall-clock budget: the front-door
    restore test in tests/test_frontend.py keeps the JSON round-trip +
    bit-exact-resume claim in tier-1 (it drives this same
    ServingEngine.restore path through ServingFrontDoor.restore)."""
    cfg, model = tiny_model
    prompts, max_new, wants = workload
    eng = ServingEngine(model, num_slots=3, block_size=4,
                        prefill_chunk=4, decode_quantum=3,
                        resilience=_policy())
    reqs = _submit_all(eng, prompts, max_new)
    while len(reqs[0].tokens) < 2:
        eng.step()
    pre = {str(r.req_id): list(r.tokens) for r in eng.completed}
    snap = json.loads(json.dumps(eng.snapshot()))
    assert snap["kind"] == "serving_engine_snapshot"
    assert len(snap["inflight"]) + len(pre) == len(reqs)
    eng2 = ServingEngine.restore(snap, model, resilience=_policy())
    eng2.run()
    done = dict(pre)
    done.update({str(r.req_id): list(r.tokens)
                 for r in eng2.completed})
    for req, p, want in zip(reqs, prompts, wants):
        got = np.concatenate([p, np.asarray(done[str(req.req_id)],
                                            np.int32)])
        np.testing.assert_array_equal(got, want)
    # restored requests resumed, not re-emitted: tokens grew past the
    # snapshot point exactly once
    assert eng2.scheduler.finished_total == len(snap["inflight"])
    assert eng2.pool.fragmentation_stats()["blocks_in_use"] == 1


# ------------------------------------------------ chaos soak
@pytest.mark.slow
def test_chaos_soak_smoke(tiny_model):
    """Bounded seeded soak (faults x preempt x COW): every stream ends
    with a definite finish_reason, non-poisoned streams are bit-exact
    vs the clean arm, nothing leaks. Replayable from the seed.

    Slow-tiered for the tier-1 wall-clock budget: the `obs check`
    resilience smoke in scripts/check_graphs.sh runs the same bounded
    soak on every gate, and the 200-round soak below is the
    acceptance run."""
    cfg, model = tiny_model
    report = run_soak(model, rounds=12, seed=4)
    assert report["requests"] > 0
    assert report["faults_injected"] > 0
    assert report["bitexact_streams"] == (report["requests"]
                                          - len(report["poisoned"]))


@pytest.mark.slow
def test_chaos_soak_200_rounds(tiny_model):
    """The acceptance soak: 200 seeded rounds of
    faults x preemption x COW on the plain engine (~8 min on CPU —
    the eager mixed-prefill step dominates)."""
    cfg, model = tiny_model
    report = run_soak(model, rounds=200, seed=0)
    assert report["rounds"] == 200
    assert report["faults_injected"] > 20
    assert report["preemptions"] > 0
    assert report["quarantined"]       # poisons actually fired


@pytest.mark.slow
def test_chaos_soak_speculative(tiny_model, tiny_draft):
    """The speculative arm of the acceptance soak: 60 rounds of
    faults x spec x preempt x COW, long enough for the spec-disable
    ladder to trip mid-run (~4 min on CPU)."""
    cfg, model = tiny_model
    report = run_soak(model, spec_draft=tiny_draft, rounds=60, seed=0)
    assert report["faults_injected"] > 10
    assert report["spec_disabled"]
