"""Optimizer + LR scheduler + clip + AMP tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def quad_minimize(opt_factory, steps=150, tol=0.1):
    p = paddle.to_tensor([0.0, 0.0], stop_gradient=False)
    opt = opt_factory([p])
    for _ in range(steps):
        loss = ((p - paddle.to_tensor([3.0, -2.0])) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(p.numpy(), [3.0, -2.0], atol=tol)


@pytest.mark.parametrize(
    "factory",
    [
        lambda ps: paddle.optimizer.SGD(0.1, parameters=ps),
        lambda ps: paddle.optimizer.Momentum(0.05, 0.9, parameters=ps),
        lambda ps: paddle.optimizer.Adam(0.3, parameters=ps),
        lambda ps: paddle.optimizer.AdamW(0.3, parameters=ps, weight_decay=0.0),
        lambda ps: paddle.optimizer.RMSProp(0.1, parameters=ps),
        lambda ps: paddle.optimizer.Adagrad(0.9, parameters=ps),
        lambda ps: paddle.optimizer.Adamax(0.3, parameters=ps),
        lambda ps: paddle.optimizer.Lamb(0.1, lamb_weight_decay=0.0, parameters=ps),
    ],
)
def test_optimizers_converge(factory):
    quad_minimize(factory)


def test_adam_matches_reference_formula():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    opt = paddle.optimizer.Adam(0.1, parameters=[p])
    (p * 3.0).sum().backward()
    opt.step()
    # one Adam step with g=3: m=0.3*? — closed form below
    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.1
    g = 3.0
    m = (1 - b1) * g
    v = (1 - b2) * g * g
    m_hat = m / (1 - b1)
    v_hat = v / (1 - b2)
    expect = 1.0 - lr * m_hat / (np.sqrt(v_hat) + eps)
    np.testing.assert_allclose(p.numpy(), [expect], rtol=1e-5)


def test_weight_decay_l2():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    opt = paddle.optimizer.SGD(0.1, parameters=[p], weight_decay=0.5)
    paddle.to_tensor([0.0])
    (p * 0.0).sum().backward()
    opt.step()
    # grad = 0 + wd*p = 0.5 → p = 1 - 0.1*0.5
    np.testing.assert_allclose(p.numpy(), [0.95], rtol=1e-6)


def test_adamw_decoupled_decay():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    opt = paddle.optimizer.AdamW(0.1, parameters=[p], weight_decay=0.1)
    (p * 0.0).sum().backward()
    opt.step()
    # zero grad → only decoupled decay: p -= lr*wd*p
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.1 * 1.0], rtol=1e-5)


def test_grad_clip_global_norm():
    p1 = paddle.to_tensor([3.0], stop_gradient=False)
    p2 = paddle.to_tensor([4.0], stop_gradient=False)
    clip = paddle.nn.ClipGradByGlobalNorm(1.0)
    opt = paddle.optimizer.SGD(1.0, parameters=[p1, p2], grad_clip=clip)
    (p1 * 3.0 + p2 * 4.0).sum().backward()
    opt.step()
    # grads (3,4): global norm 5 → scaled by 1/5 → (0.6, 0.8)
    np.testing.assert_allclose(p1.numpy(), [3.0 - 0.6], rtol=1e-5)
    np.testing.assert_allclose(p2.numpy(), [4.0 - 0.8], rtol=1e-5)


def test_lr_scheduler_step():
    sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    p = paddle.to_tensor([1.0], stop_gradient=False)
    opt = paddle.optimizer.SGD(sched, parameters=[p])
    lrs = []
    for i in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)


def test_cosine_warmup_schedulers():
    cos = paddle.optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
    vals = []
    for _ in range(11):
        vals.append(cos())
        cos.step()
    np.testing.assert_allclose(vals[0], 1.0, atol=1e-6)
    np.testing.assert_allclose(vals[10], 0.0, atol=1e-6)
    warm = paddle.optimizer.lr.LinearWarmup(0.5, warmup_steps=5, start_lr=0.0, end_lr=0.5)
    ws = []
    for _ in range(6):
        ws.append(warm())
        warm.step()
    np.testing.assert_allclose(ws[:5], [0.0, 0.1, 0.2, 0.3, 0.4], atol=1e-6)


def test_optimizer_state_dict_roundtrip():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    p.name = "p0"
    opt = paddle.optimizer.Adam(0.1, parameters=[p])
    (p * 2).sum().backward()
    opt.step()
    sd = opt.state_dict()
    p2 = paddle.to_tensor([1.0], stop_gradient=False)
    p2.name = "p0"
    opt2 = paddle.optimizer.Adam(0.1, parameters=[p2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    st = opt2._states[id(p2)]
    np.testing.assert_allclose(
        np.asarray(st["moment1"]), np.asarray(opt._states[id(p)]["moment1"])
    )


def test_amp_autocast_bf16():
    with paddle.amp.auto_cast(dtype="bfloat16"):
        out = paddle.matmul(paddle.ones([2, 2]), paddle.ones([2, 2]))
        assert out.dtype.name == "bfloat16"
        # black-listed op stays fp32
        s = paddle.nn.functional.softmax(out)
        assert s.dtype.name == "float32"
    out2 = paddle.matmul(paddle.ones([2, 2]), paddle.ones([2, 2]))
    assert out2.dtype.name == "float32"


def test_grad_scaler_skips_inf():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    opt = paddle.optimizer.SGD(0.1, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    loss = (p * float("inf")).sum()
    scaler.minimize(opt, scaler.scale(loss))
    np.testing.assert_allclose(p.numpy(), [1.0])  # step skipped
    assert scaler._scale == 4.0  # decr after 2 bad steps (default)


def test_master_weights_multi_precision():
    p = paddle.Parameter(np.ones(4, np.float16))
    opt = paddle.optimizer.Adam(0.1, parameters=[p], multi_precision=True)
    (p.astype("float32") * 2).sum().backward()
    assert p.grad is not None
    opt.step()
    st = opt._states[id(p)]
    assert "master" in st and str(st["master"].dtype) == "float32"
    assert p.dtype.name == "float16"


def test_adam_bf16_moments_close_to_f32():
    """moment_dtype='bfloat16' halves state HBM; updates stay f32-math
    and track the f32-moment trajectory closely."""
    import paddle_tpu.nn as nn

    def train(moment_dtype, steps=20):
        paddle.seed(0)
        m = nn.Linear(16, 16)
        opt = paddle.optimizer.AdamW(
            1e-2, parameters=m.parameters(), moment_dtype=moment_dtype)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 16).astype("f4"))
        losses = []
        for _ in range(steps):
            loss = ((m(x) - x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses, opt

    l32, o32 = train("float32")
    l16, o16 = train("bfloat16")
    # state dtype actually halved
    st = next(iter(o16._states.values()))
    assert str(st["moment1"].dtype) == "bfloat16"
    assert str(next(iter(o32._states.values()))["moment1"].dtype) == "float32"
    # loss curves agree to bf16 tolerance and both decrease
    assert l16[-1] < l16[0] and l32[-1] < l32[0]
    np.testing.assert_allclose(l16, l32, rtol=0.05, atol=1e-3)


@pytest.mark.parametrize("cls_name", ["Rprop", "NAdam", "RAdam", "ASGD"])
def test_new_optimizers_converge(cls_name):
    import paddle_tpu.nn as nn

    paddle.seed(0)
    m = nn.Linear(4, 4)
    cls = getattr(paddle.optimizer, cls_name)
    opt = cls(0.01, parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 4).astype("f4"))
    losses = []
    for _ in range(30):
        loss = ((m(x) - x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses[:3] + losses[-3:]


def test_lbfgs_quadratic_converges_fast():
    import paddle_tpu.nn as nn

    paddle.seed(0)
    # strongly convex quadratic: LBFGS should crush it in a few closures
    target = np.random.RandomState(1).randn(6).astype("f4")
    w = paddle.to_tensor(np.zeros(6, "f4"))
    w.stop_gradient = False
    opt = paddle.optimizer.LBFGS(
        learning_rate=0.5, max_iter=4, parameters=[w])

    def closure():
        opt.clear_grad()
        loss = ((w - paddle.to_tensor(target)) ** 2).sum()
        loss.backward()
        return loss

    for _ in range(5):
        loss = opt.step(closure)
    np.testing.assert_allclose(
        np.asarray(w._value), target, rtol=1e-2, atol=1e-2)


def test_lbfgs_builds_curvature_history():
    """Regression: the (s, y) pairs must actually accumulate (a
    bookkeeping bug once made LBFGS silently degrade to plain GD)."""
    rng = np.random.RandomState(2)
    A = rng.randn(8, 8).astype("f4")
    A = A @ A.T + 8 * np.eye(8, dtype="f4")  # SPD, conditioned
    b = rng.randn(8).astype("f4")
    w = paddle.to_tensor(np.zeros(8, "f4"))
    w.stop_gradient = False
    opt = paddle.optimizer.LBFGS(
        learning_rate=0.05, max_iter=3, parameters=[w])

    def closure():
        opt.clear_grad()
        Aw = paddle.to_tensor(A) @ w
        loss = 0.5 * (w * Aw).sum() - (paddle.to_tensor(b) * w).sum()
        loss.backward()
        return loss

    for _ in range(4):
        opt.step(closure)
    assert len(opt._hist) > 0  # curvature pairs recorded
    expect = np.linalg.solve(A, b)
    np.testing.assert_allclose(
        np.asarray(w._value), expect, rtol=0.05, atol=0.05)
    # state roundtrip keeps history
    st = opt.state_dict()
    opt2 = paddle.optimizer.LBFGS(parameters=[w])
    opt2.set_state_dict(st)
    assert len(opt2._hist) == len(opt._hist)


def test_asgd_averages_gradients():
    # constant grad g: after warmup d/n == g, so same as SGD; alternating
    # grads must average out
    w = paddle.to_tensor(np.zeros(1, "f4"))
    w.stop_gradient = False
    opt = paddle.optimizer.ASGD(0.1, batch_num=2, parameters=[w])
    for i in range(4):
        sign = 1.0 if i % 2 == 0 else -1.0
        loss = (w * sign).sum()  # d/dw = sign
        loss.backward()
        opt.step()
        opt.clear_grad()
    # alternating +-1 grads with window 2 → net movement ~ first step only
    assert abs(float(w._value[0])) < 0.2


def test_lbfgs_frozen_param_offsets_stay_aligned():
    """Regression (round-2 advisor): a no-grad param in the parameter
    list must not desync the flatten/unflatten offsets."""
    paddle.seed(0)
    target = np.random.RandomState(3).randn(4).astype("f4")
    frozen = paddle.to_tensor(np.full((3, 2), 7.0, "f4"))  # stop_gradient
    w = paddle.to_tensor(np.zeros(4, "f4"))
    w.stop_gradient = False
    opt = paddle.optimizer.LBFGS(
        learning_rate=0.5, max_iter=4, parameters=[frozen, w])

    def closure():
        opt.clear_grad()
        loss = ((w - paddle.to_tensor(target)) ** 2).sum()
        loss.backward()
        return loss

    for _ in range(5):
        opt.step(closure)
    np.testing.assert_allclose(
        np.asarray(w._value), target, rtol=1e-2, atol=1e-2)
    np.testing.assert_array_equal(np.asarray(frozen._value),
                                  np.full((3, 2), 7.0, "f4"))
