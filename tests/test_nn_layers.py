"""nn.Layer system + layer zoo tests (reference test analog:
test/legacy_test layer tests — SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_layer_registration():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(2, 3)
            self.w = self.create_parameter([2, 2])
            self.register_buffer("buf", paddle.zeros([1]))

        def forward(self, x):
            return self.fc(x)

    m = M()
    names = [n for n, _ in m.named_parameters()]
    assert set(names) == {"fc.weight", "fc.bias", "w"}
    sd = m.state_dict()
    assert "buf" in sd
    assert len(m.sublayers()) == 1


def test_set_state_dict_shape_check():
    m = nn.Linear(2, 3)
    sd = m.state_dict()
    sd2 = {k: v.numpy() for k, v in sd.items()}
    sd2["weight"] = np.zeros((3, 3), np.float32)
    with pytest.raises(ValueError):
        m.set_state_dict(sd2)


def test_train_eval_propagates():
    m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    m.eval()
    assert not m[1].training
    m.train()
    assert m[1].training


def test_linear_matches_numpy():
    m = nn.Linear(4, 3)
    x = np.random.randn(5, 4).astype(np.float32)
    out = m(paddle.to_tensor(x))
    ref = x @ m.weight.numpy() + m.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_conv2d_matches_torch_style_ref():
    # oracle: scipy-free direct conv via numpy
    m = nn.Conv2D(2, 3, 3, padding=1)
    x = np.random.randn(1, 2, 5, 5).astype(np.float32)
    out = m(paddle.to_tensor(x))
    assert out.shape == [1, 3, 5, 5]
    # numeric check against explicit loop conv
    w, b = m.weight.numpy(), m.bias.numpy()
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    ref = np.zeros((1, 3, 5, 5), np.float32)
    for oc in range(3):
        for i in range(5):
            for j in range(5):
                ref[0, oc, i, j] = (
                    xp[0, :, i : i + 3, j : j + 3] * w[oc]
                ).sum() + b[oc]
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_batchnorm_running_stats():
    bn = nn.BatchNorm1D(4)
    x = paddle.randn([16, 4])
    bn.train()
    y = bn(x)
    # batch-normalized output: near zero mean, unit var
    np.testing.assert_allclose(y.numpy().mean(0), 0, atol=1e-5)
    assert not np.allclose(bn._mean.numpy(), 0)
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [16, 4]


def test_layernorm_vs_numpy():
    ln = nn.LayerNorm(8)
    x = np.random.randn(3, 8).astype(np.float32)
    out = ln(paddle.to_tensor(x))
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_rmsnorm_vs_numpy():
    m = nn.RMSNorm(8)
    x = np.random.randn(2, 8).astype(np.float32)
    out = m(paddle.to_tensor(x))
    ref = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    out = emb(paddle.to_tensor([[0, 1], [2, 0]]))
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], 0)
    np.testing.assert_allclose(out.numpy()[1, 1], 0)


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    y = d(x)
    kept = (y.numpy() != 0).mean()
    assert 0.3 < kept < 0.7
    np.testing.assert_allclose(y.numpy()[y.numpy() != 0], 2.0)  # upscale
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), 1.0)


def test_maxpool_avgpool():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = F.max_pool2d(x, 2)
    np.testing.assert_allclose(mp.numpy()[0, 0], [[5, 7], [13, 15]])
    ap = F.avg_pool2d(x, 2)
    np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_adaptive_avg_pool():
    x = paddle.randn([2, 3, 7, 7])
    out = F.adaptive_avg_pool2d(x, 1)
    np.testing.assert_allclose(
        out.numpy().squeeze(), x.numpy().mean((2, 3)), rtol=1e-5
    )


def test_cross_entropy_matches_manual():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.array([0, 2, 4, 1])
    out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(4), labels]).mean()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_cross_entropy_ignore_index():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.array([0, -100, 4, -100])
    out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[[0, 2], [0, 4]]).mean()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_multi_head_attention_shapes():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 6, 16])
    out = mha(q)
    assert out.shape == [2, 6, 16]


def test_mha_cache_decode():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 1, 16])
    cache = mha.gen_cache(x)
    out, cache = mha(x, x, x, cache=cache)
    assert cache.k.shape[1] == 1
    out, cache = mha(x, x, x, cache=cache)
    assert cache.k.shape[1] == 2


def test_transformer_full():
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32)
    src = paddle.randn([2, 5, 16])
    tgt = paddle.randn([2, 3, 16])
    out = model(src, tgt)
    assert out.shape == [2, 3, 16]


def test_lstm_grad_flows():
    lstm = nn.LSTM(4, 8)
    x = paddle.randn([2, 5, 4])
    out, (h, c) = lstm(x)
    out.sum().backward()
    cell = lstm.rnns[0].cell
    assert cell.weight_ih.grad is not None


def test_sequential_and_layerlist():
    s = nn.Sequential(("a", nn.Linear(2, 2)), ("b", nn.ReLU()))
    assert len(s) == 2
    out = s(paddle.ones([1, 2]))
    assert out.shape == [1, 2]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8


def test_forward_hooks():
    m = nn.Linear(2, 2)
    calls = []
    h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    m(paddle.ones([1, 2]))
    assert calls == [1]
    h.remove()
    m(paddle.ones([1, 2]))
    assert calls == [1]


def test_interpolate_nearest():
    x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = F.interpolate(x, scale_factor=2, mode="nearest")
    assert out.shape == [1, 1, 4, 4]
    np.testing.assert_allclose(out.numpy()[0, 0, :2, :2], 0)


def test_scaled_dot_product_attention_causal():
    q = paddle.randn([1, 4, 2, 8])
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    assert out.shape == [1, 4, 2, 8]
    # position 0 attends only to itself → equals v[0]
    np.testing.assert_allclose(
        out.numpy()[0, 0], q.numpy()[0, 0], rtol=1e-4, atol=1e-5
    )
