"""Perf sentinel (ISSUE 10): PerfBudget declarations, three-shape
artifact normalization, the deterministic BENCH_INDEX, staleness
detection, and the gate over the repo's REAL checked-in artifacts —
including the doctored-artifact acceptance case (a spec ratio pushed
below its floor must fail with a readable field-level diff)."""
import glob
import json
import os

import pytest

from paddle_tpu.analysis.perf_budget import (
    INDEX_VERSION, PerfBudget, PerfBudgetViolation, build_index,
    check_perf, compare_index, default_perf_budgets,
    normalize_artifact,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _repo_artifacts():
    paths = [p for p in glob.glob(os.path.join(REPO, "BENCH_*.json"))
             if os.path.basename(p) != "BENCH_INDEX.json"]
    paths += glob.glob(os.path.join(REPO, "MULTICHIP_*.json"))
    return paths


# ------------------------------------------------------- declarations
def test_budget_declaration_is_loud():
    with pytest.raises(TypeError, match="unknown perf-budget field"):
        PerfBudget("x", "A.json", "m", floor=1.0, celing=2.0)
    with pytest.raises(TypeError, match="floor and/or ceiling"):
        PerfBudget("x", "A.json", "m")
    with pytest.raises(TypeError, match="noise_frac"):
        PerfBudget("x", "A.json", "m", floor=1.0, noise_frac=1.5)


def test_noise_band_widens_both_bounds():
    b = PerfBudget("x", "A.json", "m", floor=2.0, ceiling=4.0,
                   noise_frac=0.1)
    assert b.effective_floor == pytest.approx(1.8)
    assert b.effective_ceiling == pytest.approx(4.4)
    assert b.check_row({"metric": "m", "value": 1.85}) == []
    v = b.check_row({"metric": "m", "value": 1.7})
    assert len(v) == 1 and "< floor 2" in v[0] and "10%" in v[0]
    v = b.check_row({"metric": "m", "value": 4.5})
    assert len(v) == 1 and "> ceiling 4" in v[0]
    # a bool is not a measurement; neither is a missing field
    assert "schema drift" in b.check_row({"metric": "m",
                                          "value": True})[0]
    assert "schema drift" in b.check_row({"metric": "m"})[0]


# ------------------------------------------------------ normalization
def test_normalize_three_artifact_shapes():
    flat = normalize_artifact(
        {"metric": "m", "value": 1.5, "unit": "%", "obs": {"x": 1},
         "passes": True}, "F.json")
    assert flat == {"artifact": "F.json", "kind": "bench", "rows": [
        {"metric": "m", "passes": True, "unit": "%", "value": 1.5}]}
    rows = normalize_artifact(
        {"round": 5, "rows": [{"metric": "a", "value": 1},
                              {"metric": "b", "value": 2,
                               "detail": [1, 2]}]}, "R.json")
    assert [r["metric"] for r in rows["rows"]] == ["a", "b"]
    assert "detail" not in rows["rows"][1]  # nested values dropped
    drv = normalize_artifact(
        {"n": 8, "rc": 1, "ok": False, "tail": "boom"}, "D.json")
    assert drv["kind"] == "driver"
    assert drv["rows"] == [
        {"metric": "driver_exit", "rc": 1, "n": 8, "ok": False}]


def test_normalize_rejects_drift_naming_the_file():
    with pytest.raises(ValueError, match="X.json.*JSON object"):
        normalize_artifact([1, 2], "X.json")
    with pytest.raises(ValueError, match="X.json.*non-empty list"):
        normalize_artifact({"rows": []}, "X.json")
    with pytest.raises(ValueError, match=r"X.json: rows\[1\]"):
        normalize_artifact(
            {"rows": [{"metric": "a"}, {"value": 2}]}, "X.json")
    with pytest.raises(ValueError, match="X.json.*'rc' must be an int"):
        normalize_artifact({"rc": "one"}, "X.json")
    with pytest.raises(ValueError, match="unrecognized artifact shape"):
        normalize_artifact({"something": 1}, "X.json")


# ------------------------------------------------- index + staleness
def test_index_is_deterministic_and_staleness_is_a_diff(tmp_path):
    a = tmp_path / "BENCH_A.json"
    b = tmp_path / "BENCH_B.json"
    a.write_text(json.dumps({"metric": "m", "value": 1.0}))
    b.write_text(json.dumps({"rc": 0, "n": 2}))
    budgets = [PerfBudget("m-floor", "BENCH_A.json", "m", floor=0.5)]
    idx = build_index([str(b), str(a)], budgets)  # order-insensitive
    assert idx == build_index([str(a), str(b)], budgets)
    assert idx["version"] == INDEX_VERSION
    assert [x["artifact"] for x in idx["artifacts"]] == [
        "BENCH_A.json", "BENCH_B.json"]
    assert compare_index(idx, idx) == []
    # the artifact moves but the index does not -> field-level line
    a.write_text(json.dumps({"metric": "m", "value": 2.0}))
    fresh = build_index([str(a), str(b)], budgets)
    diffs = compare_index(fresh, idx)
    assert len(diffs) == 1
    assert "rows[0].value indexed as 1.0 but artifact has 2.0" in \
        diffs[0]
    # a new artifact that never got indexed is a hole, not a pass
    c = tmp_path / "BENCH_C.json"
    c.write_text(json.dumps({"metric": "m2", "value": 3.0}))
    fresh = build_index([str(a), str(b), str(c)], budgets)
    assert any("BENCH_C.json: artifact on disk but not indexed" in d
               for d in compare_index(fresh, idx))
    # a budget that moved without --update is drift too
    loose = [PerfBudget("m-floor", "BENCH_A.json", "m", floor=0.1)]
    assert any("guarded budget declarations drifted" in d
               for d in compare_index(build_index([str(a)], loose),
                                      build_index([str(a)], budgets)))


def test_gate_over_real_checked_in_artifacts():
    """The repo's own trajectory must pass its own sentinel, and the
    checked-in BENCH_INDEX.json must be fresh (what check_perf.sh
    runs, minus the CLI)."""
    paths = _repo_artifacts()
    budgets = default_perf_budgets()
    index = build_index(paths, budgets)
    with open(os.path.join(REPO, "BENCH_INDEX.json")) as f:
        checked_in = json.load(f)
    assert compare_index(index, checked_in) == []
    lines = check_perf(index, budgets)
    assert len(lines) == len(budgets)
    assert all(ln.startswith("ok  ") for ln in lines)


def test_doctored_artifact_fails_with_readable_diff(tmp_path):
    """Acceptance case: copy the artifacts, push the spec-serving
    ratio below its floor, rebuild — the gate must fail naming the
    file, metric, measured value, floor and band in one line."""
    paths = []
    for p in _repo_artifacts():
        dst = tmp_path / os.path.basename(p)
        with open(p) as f:
            doc = json.load(f)
        if dst.name == "BENCH_SPEC_r07.json":
            for row in doc["rows"]:  # rows-style artifact
                if row["metric"].startswith(
                        "speculative_serving_speedup"):
                    row["value"] = 0.9  # quietly regressed
        dst.write_text(json.dumps(doc))
        paths.append(str(dst))
    budgets = default_perf_budgets()
    with pytest.raises(PerfBudgetViolation) as ei:
        check_perf(build_index(paths, budgets), budgets)
    assert len(ei.value.violations) == 1
    line = ei.value.violations[0]
    assert "BENCH_SPEC_r07.json" in line
    assert "speculative_serving_speedup" in line
    assert "0.9 < floor 1.1" in line
    assert "noise band 5% -> 1.045" in line
    assert "[spec-serving-speedup]" in line


def test_missing_artifact_or_metric_is_a_violation(tmp_path):
    """A deleted artifact (or renamed metric) must fail the budget
    that guards it, not silently skip."""
    a = tmp_path / "BENCH_A.json"
    a.write_text(json.dumps({"metric": "renamed", "value": 9.0}))
    budgets = [PerfBudget("gone", "BENCH_GONE.json", "m", floor=1.0),
               PerfBudget("renamed", "BENCH_A.json", "m", floor=1.0)]
    with pytest.raises(PerfBudgetViolation) as ei:
        check_perf(build_index([str(a)], budgets), budgets)
    v = ei.value.violations
    assert any("BENCH_GONE.json: artifact missing" in x for x in v)
    assert any("no row with metric 'm'" in x
               and "'renamed'" in x for x in v)


def test_default_budgets_do_not_guard_driver_history():
    """Driver dumps are history, not claims: MULTICHIP_r02 honestly
    recorded a libtpu-mismatch failure (rc=1) and the sentinel must
    index it without demanding it be rewritten."""
    budgets = default_perf_budgets()
    assert all(not b.artifact.startswith(("BENCH_r", "MULTICHIP"))
               for b in budgets)
    with open(os.path.join(REPO, "MULTICHIP_r02.json")) as f:
        row = normalize_artifact(json.load(f),
                                 "MULTICHIP_r02.json")["rows"][0]
    assert row["rc"] == 1  # indexed as-is
    check_perf(build_index(_repo_artifacts(), budgets), budgets)
