"""Context parallelism (sep axis): ring attention + Ulysses parallel==serial
oracles on the 8-device virtual CPU mesh (SURVEY.md §5 long-context)."""
import numpy as np
import pytest
import jax
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.parallel import mesh as mesh_state
from paddle_tpu.distributed.fleet.meta_parallel import (
    ring_flash_attention, ulysses_attention, sep_attention,
    split_inputs_sequence_dim,
)


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def _mk_qkv(b=2, s=64, h=4, hk=None, d=16, seed=0):
    rng = np.random.RandomState(seed)
    hk = hk or h
    q = paddle.to_tensor(rng.randn(b, s, h, d).astype("float32"))
    k = paddle.to_tensor(rng.randn(b, s, hk, d).astype("float32"))
    v = paddle.to_tensor(rng.randn(b, s, hk, d).astype("float32"))
    for t in (q, k, v):
        t.stop_gradient = False
    return q, k, v


def _sep_mesh(n=4):
    devs = np.array(jax.devices()[:n]).reshape(1, n)
    mesh = Mesh(devs, ("dp", "sep"))
    mesh_state.set_mesh(mesh)
    return mesh


@pytest.mark.parametrize("causal", [False, True])
def test_ring_equals_serial(causal):
    q, k, v = _mk_qkv()
    ref = F.scaled_dot_product_attention(q, k, v, is_causal=causal)
    _sep_mesh(4)
    out = ring_flash_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(ref._value), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_equals_serial(causal):
    q, k, v = _mk_qkv()
    ref = F.scaled_dot_product_attention(q, k, v, is_causal=causal)
    _sep_mesh(4)
    out = ulysses_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(ref._value), rtol=2e-5, atol=2e-5
    )


def test_ring_gqa():
    q, k, v = _mk_qkv(h=8, hk=2)
    ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    _sep_mesh(4)
    out = ring_flash_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(ref._value), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("schedule", ["ring", "ulysses"])
def test_sep_attention_grads_match(schedule):
    q1, k1, v1 = _mk_qkv(seed=3)
    ref = F.scaled_dot_product_attention(q1, k1, v1, is_causal=True)
    loss1 = (ref * ref).sum()
    g_ref = paddle.grad(loss1, [q1, k1, v1])

    _sep_mesh(4)
    q2, k2, v2 = _mk_qkv(seed=3)
    out = sep_attention(q2, k2, v2, is_causal=True, schedule=schedule)
    loss2 = (out * out).sum()
    g = paddle.grad(loss2, [q2, k2, v2])
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(
            np.asarray(a._value), np.asarray(b._value), rtol=1e-4, atol=1e-4
        )


def test_ulysses_head_divisibility_error():
    _sep_mesh(4)
    q, k, v = _mk_qkv(h=2, hk=2)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v)


def test_no_mesh_falls_back_to_serial():
    q, k, v = _mk_qkv()
    out = ring_flash_attention(q, k, v, is_causal=True)
    ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(ref._value), rtol=1e-6
    )


def test_split_inputs_sequence_dim():
    _sep_mesh(4)
    x = paddle.to_tensor(np.random.randn(2, 64, 8).astype("float32"))
    y = split_inputs_sequence_dim(x)
    sh = y._value.sharding
    assert sh.spec[1] == "sep"


def test_ring_in_jit_under_mesh():
    """The ring schedule must compile inside jax.jit (train-step path)."""
    _sep_mesh(4)
    q, k, v = _mk_qkv(s=32)

    import jax.numpy as jnp

    def f(qv, kv, vv):
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.core import autograd

        with autograd.no_grad():
            out = ring_flash_attention(
                Tensor(qv, stop_gradient=True),
                Tensor(kv, stop_gradient=True),
                Tensor(vv, stop_gradient=True),
                is_causal=True,
            )
        return out._value

    jitted = jax.jit(f)
    got = jitted(q._value, k._value, v._value)
    ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref._value), rtol=2e-5, atol=2e-5
    )


@pytest.mark.xfail(
    reason="pre-existing under this container's jax: XLA donation "
           "aliases a replicated param buffer to a resharded output "
           "('Expected aliased input ... to have the same size') in "
           "the dp2xmp2xsep2 hybrid step; present at seed",
    strict=False)
def test_llama_ring_cp_train_matches_serial():
    """Full Llama train step with ring context parallelism over sep==2
    matches the serial step (sep axis end-to-end through the model)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.nlp import (
        LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    )
    from paddle_tpu.jit.train import JittedTrainStep

    def losses(sep, steps=3):
        mesh_state.set_mesh(None)
        try:
            if sep > 1:
                strategy = fleet.DistributedStrategy()
                strategy.hybrid_configs = {
                    "dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                    "sep_degree": sep,
                }
                fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            cfg = LlamaConfig.tiny(
                tensor_parallel=True,
                context_parallel="ring" if sep > 1 else None,
            )
            m = LlamaForCausalLM(cfg)
            crit = LlamaPretrainingCriterion()
            opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
            step = JittedTrainStep(m, lambda o, l: crit(o, l), opt)
            ids = paddle.to_tensor(
                np.random.RandomState(1).randint(0, 128, (4, 32)))
            return [float(step(ids, ids)) for _ in range(steps)]
        finally:
            # a mid-step failure must not leak the hybrid mesh into
            # later tests' device_put placements
            mesh_state.set_mesh(None)

    lp = losses(sep=2)
    ls = losses(sep=1)
    np.testing.assert_allclose(lp, ls, rtol=5e-4, atol=5e-5)


def test_custom_scale_consistent_with_and_without_mesh():
    q, k, v = _mk_qkv()
    no_mesh = ring_flash_attention(q, k, v, is_causal=True, scale=0.5)
    _sep_mesh(4)
    with_mesh = ring_flash_attention(q, k, v, is_causal=True, scale=0.5)
    np.testing.assert_allclose(
        np.asarray(with_mesh._value), np.asarray(no_mesh._value),
        rtol=2e-5, atol=2e-5,
    )


def test_split_inputs_skips_non_seq_leaves():
    _sep_mesh(4)
    batch = {
        "input_ids": paddle.to_tensor(np.zeros((2, 64), "int32")),
        "lengths": paddle.to_tensor(np.zeros((2,), "int32")),
        "mask": None,
    }
    out = split_inputs_sequence_dim(batch)
    assert out["mask"] is None
    assert out["lengths"].shape == [2]
    assert out["input_ids"]._value.sharding.spec[1] == "sep"


def test_seq_divisibility_error():
    _sep_mesh(4)
    q, k, v = _mk_qkv(s=66)
    with pytest.raises(ValueError, match="seq len"):
        ring_flash_attention(q, k, v, is_causal=True)
