"""SD-UNet conditional diffusion (BASELINE config #5): forward shapes,
training step, and the one-program jitted DDIM denoising loop."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.models import (
    SDUNetConfig, UNet2DConditionModel, DDIMScheduler, ddim_sample,
)


def _build(b=2):
    paddle.seed(0)
    cfg = SDUNetConfig.tiny()
    unet = UNet2DConditionModel(cfg)
    rng = np.random.RandomState(0)
    lat = paddle.to_tensor(
        rng.randn(b, cfg.in_channels, cfg.sample_size,
                  cfg.sample_size).astype("f4"))
    ctx = paddle.to_tensor(
        rng.randn(b, 6, cfg.cross_attention_dim).astype("f4"))
    return cfg, unet, lat, ctx


def test_unet_forward_shape():
    cfg, unet, lat, ctx = _build()
    t = paddle.to_tensor(np.array([10, 500], "i4"))
    out = unet(lat, t, ctx)
    assert out.shape == list(lat.shape)


@pytest.mark.slow  # ~20s (full UNet fwd+bwd+opt, 3 steps); the
# forward-shape test keeps the architecture covered in tier-1 — the
# 870s ceiling forced a re-tier as the suite grew (PR 7)
def test_unet_denoising_train_step():
    cfg, unet, lat, ctx = _build()
    sched = DDIMScheduler()
    opt = paddle.optimizer.AdamW(1e-3, parameters=unet.parameters())
    rng = np.random.RandomState(1)
    noise = paddle.to_tensor(np.asarray(lat._value) * 0.0 +
                             rng.randn(*lat.shape).astype("f4"))
    t = paddle.to_tensor(np.array([100, 700], "i4"))
    losses = []
    for _ in range(4):
        eps = unet(lat, t, ctx)
        loss = ((eps - noise) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_ddim_sample_one_program():
    cfg, unet, lat, ctx = _build()
    unet.eval()
    out = ddim_sample(unet, lat, ctx, num_inference_steps=4)
    assert out.shape == list(lat.shape)
    assert np.isfinite(np.asarray(out._value)).all()
    # deterministic (eta=0): same inputs, same sample
    out2 = ddim_sample(unet, lat, ctx, num_inference_steps=4)
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(out2._value), rtol=1e-6)


def test_scheduler_timesteps_descend():
    s = DDIMScheduler(num_train_timesteps=1000)
    ts = s.timesteps(10)
    assert len(ts) == 10 and (np.diff(ts) < 0).all()


def test_ddim_loop_cached_across_calls():
    cfg, unet, lat, ctx = _build()
    unet.eval()
    ddim_sample(unet, lat, ctx, num_inference_steps=3)
    cache = unet._ddim_loops
    assert len(cache) == 1
    ddim_sample(unet, lat, ctx, num_inference_steps=3)
    assert len(cache) == 1  # same compiled loop reused


def test_scheduler_steps_validation():
    with pytest.raises(ValueError, match="num_inference_steps"):
        DDIMScheduler(num_train_timesteps=10).timesteps(20)


def test_unet_params_all_registered():
    cfg, unet, lat, ctx = _build()
    names = [n for n, _ in unet.named_parameters()]
    assert any("down_res" in n for n in names)
    assert any("up_attn" in n for n in names)
    assert any("downsamplers" in n for n in names)
