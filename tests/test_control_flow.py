"""paddle.static.nn control flow over XLA structured primitives
(SURVEY.md §2.4 dy2static row: data-dependent control flow that a trace
can't bake)."""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu.static.nn import cond, while_loop, case, switch_case


def test_cond_eager_both_branches():
    x = paddle.to_tensor(np.array(3.0, "f4"))
    out = cond(x > 0, lambda: x * 2, lambda: x - 1)
    assert float(out) == 6.0
    out = cond(x < 0, lambda: x * 2, lambda: x - 1)
    assert float(out) == 2.0


def test_cond_inside_jit_traces_lazily():
    @jax.jit
    def f(v):
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.core import autograd

        with autograd.no_grad():
            t = Tensor(v, stop_gradient=True)
            out = cond(t.sum() > 0, lambda: t * 10, lambda: t * -1)
        return out._value

    np.testing.assert_allclose(np.asarray(f(np.ones(3, "f4"))), [10] * 3)
    np.testing.assert_allclose(np.asarray(f(-np.ones(3, "f4"))), [1] * 3)


def test_cond_gradients_flow():
    x = paddle.to_tensor(np.array([2.0], "f4"))
    x.stop_gradient = False
    out = cond(x.sum() > 0, lambda: (x ** 2).sum(), lambda: x.sum())
    (g,) = paddle.grad(out, [x])
    np.testing.assert_allclose(np.asarray(g._value), [4.0], rtol=1e-6)


def test_while_loop_counts():
    i = paddle.to_tensor(np.array(0, "i4"))
    s = paddle.to_tensor(np.array(0.0, "f4"))
    i2, s2 = while_loop(
        lambda i, s: i < 5,
        lambda i, s: [i + 1, s + 2.0],
        [i, s],
    )
    assert int(i2) == 5 and float(s2) == 10.0


def test_case_and_switch():
    x = paddle.to_tensor(np.array(1.0, "f4"))
    out = case(
        [(x > 2, lambda: x * 100), (x > 0, lambda: x * 10)],
        default=lambda: x,
    )
    assert float(out) == 10.0

    idx = paddle.to_tensor(np.array(2, "i4"))
    out = switch_case(
        idx,
        {0: lambda: x + 1, 2: lambda: x + 2, 5: lambda: x + 5},
    )
    assert float(out) == 3.0
    out = switch_case(  # unknown index → default (last branch)
        paddle.to_tensor(np.array(7, "i4")),
        {0: lambda: x + 1, 2: lambda: x + 2, 5: lambda: x + 5},
    )
    assert float(out) == 6.0


def test_traced_bool_raises_helpfully():
    @jax.jit
    def f(v):
        from paddle_tpu.core.tensor import Tensor

        t = Tensor(v, stop_gradient=True)
        if t > 0:  # Python branch on traced value
            return v
        return -v

    with pytest.raises(TypeError, match="static.nn.cond"):
        f(np.ones((), "f4"))


def test_while_loop_eager_grads_unroll():
    x = paddle.to_tensor(np.array(2.0, "f4"))
    x.stop_gradient = False
    i = paddle.to_tensor(np.array(0, "i4"))
    # y = x * 2^3 after three doublings
    _, y = while_loop(
        lambda i, y: i < 3,
        lambda i, y: [i + 1, y * 2.0],
        [i, x],
    )
    (g,) = paddle.grad(y, [x])
    assert float(y) == 16.0 and float(g) == 8.0


def test_cond_single_branch_returns_none():
    x = paddle.to_tensor(np.array(-1.0, "f4"))
    assert cond(x > 0, lambda: x * 2) is None


def test_async_task_on_all_gather():
    import paddle_tpu.distributed as dist

    x = paddle.to_tensor(np.ones(4, "f4"))
    out = []
    task = dist.all_gather(out, x, sync_op=False)
    assert task.wait() and len(out) >= 1


def test_while_loop_traced_dtype_mismatch_raises():
    @jax.jit
    def f(v):
        from paddle_tpu.core.tensor import Tensor

        i = Tensor(v, stop_gradient=True)
        out = while_loop(
            lambda i: i < 3,
            lambda i: [i + 0.5],  # float out of int carry
            [i],
        )
        return out[0]._value

    with pytest.raises(TypeError, match="shape/dtype-stable"):
        f(np.array(0, "i4"))
