"""LoRA (paddle.peft): wrap/freeze/train/merge semantics on plain and
fleet-TP models (reference: paddlenlp.peft.lora — unverified, SURVEY
§0)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.peft import (
    LoRAConfig, LoRALinear, get_lora_model, lora_state_dict,
)
from paddle_tpu.parallel import mesh as mesh_state


@pytest.fixture(autouse=True)
def reset_mesh():
    yield
    mesh_state.set_mesh(None)


def _llama():
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))


def test_lora_starts_equal_and_trains_only_adapters():
    m = _llama()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 12)))
    base_out = m(ids).numpy()

    lora = get_lora_model(m, LoRAConfig(r=4, lora_alpha=8))
    # B zero-init → adapted == base at step 0
    np.testing.assert_allclose(lora(ids).numpy(), base_out,
                               rtol=1e-6, atol=1e-6)

    trainable = [n for n, p in lora.named_parameters()
                 if not p.stop_gradient]
    assert trainable and all("lora_" in n for n in trainable)
    n_train = sum(int(np.prod(p.shape)) for _, p in
                  lora.named_parameters() if not p.stop_gradient)
    n_total = sum(int(np.prod(p.shape)) for _, p in
                  lora.named_parameters())
    assert n_train < n_total * 0.1  # genuinely parameter-efficient

    from paddle_tpu.nlp import LlamaPretrainingCriterion

    crit = LlamaPretrainingCriterion()
    opt = paddle.optimizer.AdamW(
        1e-2, parameters=[p for _, p in lora.named_parameters()
                          if not p.stop_gradient])
    frozen_before = {n: p.numpy().copy()
                     for n, p in lora.named_parameters() if p.stop_gradient}
    for _ in range(2):
        loss = crit(lora(ids), ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
    # adapters moved, base stayed frozen
    changed = lora(ids).numpy()
    assert np.abs(changed - base_out).max() > 1e-5
    for n, p in lora.named_parameters():
        if p.stop_gradient:
            np.testing.assert_allclose(p.numpy(), frozen_before[n],
                                       rtol=0, atol=0, err_msg=n)

    # the adapter artifact holds only lora tensors
    sd = lora_state_dict(lora)
    assert sd and all("lora_" in k for k in sd)

    # merge folds the delta into the frozen weight: same outputs, no
    # per-step delta matmuls; unmerge restores the base exactly
    merged_out = lora.merge()(ids).numpy()
    np.testing.assert_allclose(merged_out, changed, rtol=2e-5, atol=2e-5)
    lora.unmerge()
    np.testing.assert_allclose(lora(ids).numpy(), changed,
                               rtol=2e-5, atol=2e-5)


def test_lora_jitted_train_step():
    """LoRA under the fused JittedTrainStep: only adapters update."""
    from paddle_tpu.nlp import LlamaPretrainingCriterion
    from paddle_tpu.jit.train import JittedTrainStep

    m = _llama()
    lora = get_lora_model(m, LoRAConfig(r=4, lora_alpha=8))
    crit = LlamaPretrainingCriterion()
    opt = paddle.optimizer.AdamW(
        1e-2, parameters=[p for _, p in lora.named_parameters()
                          if not p.stop_gradient])
    step = JittedTrainStep(lora, lambda o, l: crit(o, l), opt)
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 128, (2, 16)))
    l0 = float(step(ids, ids))
    l1 = float(step(ids, ids))
    assert np.isfinite([l0, l1]).all()


def test_lora_on_tp_model_matches_serial():
    """LoRA wraps the fleet mp q_proj/v_proj; parallel == serial."""
    from paddle_tpu.nlp import (
        LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    )
    from paddle_tpu.distributed import fleet

    ids_np = np.random.RandomState(2).randint(0, 128, (4, 8))

    def run(parallel):
        mesh_state.set_mesh(None)
        if parallel:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {
                "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
                "sharding_degree": 1,
            }
            fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=True))
        lora = get_lora_model(m, LoRAConfig(r=4, lora_alpha=8))
        crit = LlamaPretrainingCriterion()
        opt = paddle.optimizer.AdamW(
            1e-2, parameters=[p for _, p in lora.named_parameters()
                              if not p.stop_gradient])
        ids = paddle.to_tensor(ids_np)
        losses = []
        for _ in range(2):
            loss = crit(lora(ids), ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        mesh_state.set_mesh(None)
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=2e-4,
                               atol=1e-5)


def test_lora_bad_targets_raise():
    m = _llama()
    with pytest.raises(ValueError, match="matched no"):
        get_lora_model(m, LoRAConfig(target_modules=[".*nonexistent"]))


def test_lora_trainable_bias_scoped_to_wrapped_layers():
    """trainable_bias unfreezes ONLY wrapped-layer biases, and the
    adapter state dict carries them (a reload must reproduce the
    trained model)."""
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False,
                                          attention_bias=True))
    lora = get_lora_model(m, LoRAConfig(r=2, trainable_bias=True))
    for n, p in lora.named_parameters():
        if not p.stop_gradient and n.endswith(".bias"):
            assert ".base." in n, n  # only wrapped layers' biases
    sd = lora_state_dict(lora)
    assert any(k.endswith(".bias") for k in sd)
    assert all("lora_" in k or ".base." in k for k in sd)


def test_lora_rejects_quantized_linear_base():
    """VERDICT weak #8: a PTQ-converted (QuantizedLinear) base that
    matches target_modules must raise the QLoRA-gap error instead of
    silently falling through duck-typing (which skipped the layer and
    wrapped nothing)."""
    from paddle_tpu.nn.quant import QuantizedLinear

    m = _llama()
    attn = m.llama.layers[0].self_attn
    attn.q_proj = QuantizedLinear.from_linear(attn.q_proj)
    with pytest.raises(ValueError, match="QuantizedLinear.*QLoRA"):
        get_lora_model(m, LoRAConfig(r=2))


def test_lora_state_dict_checkpoint_roundtrip(tmp_path):
    """VERDICT item 7 (checkpointing half): the adapter artifact
    survives distributed.checkpoint save/load — loading it onto a
    FRESH base + fresh LoRA wrap restores the trained forward
    exactly."""
    from paddle_tpu.distributed.checkpoint import (
        save_state_dict, load_state_dict,
    )

    m = _llama()
    lora = get_lora_model(m, LoRAConfig(r=4, lora_alpha=8))
    # perturb the adapters so the roundtrip carries real signal (in
    # particular B != 0, else the delta is zero whatever A holds)
    rng = np.random.RandomState(3)
    for n, p in lora.named_parameters():
        if "lora_" in n:
            p.set_value(p.numpy()
                        + rng.randn(*p.shape).astype("float32") * 0.05)
    ids = paddle.to_tensor(np.random.RandomState(4).randint(0, 128,
                                                            (2, 10)))
    want = lora(ids).numpy()
    sd = lora_state_dict(lora)
    save_state_dict(sd, str(tmp_path / "adapter"))

    fresh = get_lora_model(_llama(), LoRAConfig(r=4, lora_alpha=8))
    assert np.abs(fresh(ids).numpy() - want).max() > 1e-5  # differs pre-load
    dest = {k: v for k, v in fresh.state_dict().items() if k in sd}
    assert sorted(dest) == sorted(sd)
    load_state_dict(dest, str(tmp_path / "adapter"))
    np.testing.assert_allclose(fresh(ids).numpy(), want,
                               rtol=1e-6, atol=1e-6)


def test_lora_a_init_variance_is_one_over_r():
    """ADVICE round-5 low: A ~ N(0, 1/r) means std = sqrt(1/r), not
    1/r — with std=1/r the adapter update scale shrank quadratically in
    the rank. Estimate the sample std over a wide layer."""
    paddle.seed(7)
    r = 16
    base = paddle.nn.Linear(512, 64)
    lora = LoRALinear(base, r=r, lora_alpha=32)
    a = np.asarray(lora.lora_A._value)
    assert a.shape == (512, r)
    expected = (1.0 / r) ** 0.5
    sample = a.std()
    # 512*16 samples: std estimate within ±10% of sqrt(1/r), and an
    # order of magnitude away from the buggy 1/r
    assert abs(sample - expected) < 0.1 * expected, (sample, expected)
    assert sample > 2.0 * (1.0 / r)
