"""Double backward (paddle.grad(create_graph=True)) — round-1 verdict
weak #7. Oracle: analytic derivatives and jax.grad-of-grad."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle


def test_grad_of_grad_polynomial():
    x = paddle.to_tensor(np.array([1.5, -2.0, 0.5], "f4"))
    x.stop_gradient = False
    y = (x ** 3).sum()
    (g,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(
        np.asarray(g._value), 3 * np.asarray(x._value) ** 2, rtol=1e-6
    )
    assert not g.stop_gradient  # still on the tape
    (gg,) = paddle.grad(g.sum(), [x])
    np.testing.assert_allclose(
        np.asarray(gg._value), 6 * np.asarray(x._value), rtol=1e-6
    )


def test_grad_penalty_backward_writes_leaf_grad():
    """The WGAN-GP shape: penalty on ||dD/dx|| backpropagated to params."""
    rng = np.random.RandomState(0)
    w_np = rng.randn(4, 4).astype("f4")
    x_np = rng.randn(2, 4).astype("f4")

    w = paddle.to_tensor(w_np)
    w.stop_gradient = False
    x = paddle.to_tensor(x_np)
    x.stop_gradient = False
    out = paddle.nn.functional.sigmoid(x @ w).sum()
    (gx,) = paddle.grad(out, [x], create_graph=True)
    penalty = ((gx ** 2).sum(axis=1) - 1.0) ** 2
    penalty.sum().backward()
    assert w.grad is not None

    def ref_penalty(wv):
        def d(xv):
            return jax.nn.sigmoid(xv @ wv).sum()

        gxv = jax.grad(d)(jnp.asarray(x_np))
        return (((gxv ** 2).sum(axis=1) - 1.0) ** 2).sum()

    ref = jax.grad(ref_penalty)(jnp.asarray(w_np))
    np.testing.assert_allclose(
        np.asarray(w.grad._value), np.asarray(ref), rtol=1e-4, atol=1e-5
    )


def test_second_order_through_mlp_matches_jax():
    rng = np.random.RandomState(1)
    w1_np = rng.randn(3, 8).astype("f4")
    w2_np = rng.randn(8, 1).astype("f4")
    x_np = rng.randn(5, 3).astype("f4")

    x = paddle.to_tensor(x_np)
    x.stop_gradient = False
    w1 = paddle.to_tensor(w1_np)
    w2 = paddle.to_tensor(w2_np)
    y = (paddle.tanh(x @ w1) @ w2).sum()
    (g,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad((g ** 2).sum(), [x])

    def f(xv):
        return (jnp.tanh(xv @ w1_np) @ w2_np).sum()

    def sq(xv):
        return (jax.grad(f)(xv) ** 2).sum()

    ref = jax.grad(sq)(jnp.asarray(x_np))
    np.testing.assert_allclose(
        np.asarray(g2._value), np.asarray(ref), rtol=1e-4, atol=1e-5
    )


def test_first_order_path_unchanged():
    x = paddle.to_tensor(np.array([2.0], "f4"))
    x.stop_gradient = False
    y = (x ** 2).sum()
    y.backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [4.0], rtol=1e-6)


def test_double_backward_through_pylayer():
    """PyLayer create_graph: the user backward replays grad-enabled."""
    from paddle_tpu.autograd import PyLayer

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor()
            return gy * 3.0 * x * x

    x = paddle.to_tensor(np.array([2.0, -1.0], "f4"))
    x.stop_gradient = False
    y = Cube.apply(x)
    (g,) = paddle.grad(y.sum(), [x], create_graph=True)
    np.testing.assert_allclose(
        np.asarray(g._value), 3 * np.asarray(x._value) ** 2, rtol=1e-6
    )
    (gg,) = paddle.grad(g.sum(), [x])
    np.testing.assert_allclose(
        np.asarray(gg._value), 6 * np.asarray(x._value), rtol=1e-6
    )
