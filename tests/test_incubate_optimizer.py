"""incubate.optimizer: LookAhead / ModelAverage / EMA."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate import (
    LookAhead, ModelAverage, ExponentialMovingAverage,
)


def _setup():
    paddle.seed(0)
    m = nn.Linear(4, 4)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype("f4"))
    return m, x


def test_lookahead_trains_and_syncs():
    m, x = _setup()
    inner = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    opt = LookAhead(inner, alpha=0.5, k=2)
    losses = []
    for _ in range(6):
        loss = ((m(x) - x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert opt._slow is not None


def test_lookahead_slow_weights_interpolate():
    m, x = _setup()
    w0 = np.asarray(m.weight._value).copy()
    inner = paddle.optimizer.SGD(0.5, parameters=m.parameters())
    opt = LookAhead(inner, alpha=0.0, k=1)  # alpha=0: snap back to slow
    loss = ((m(x) - x) ** 2).mean()
    loss.backward()
    opt.step()
    np.testing.assert_allclose(
        np.asarray(m.weight._value), w0, rtol=1e-6)  # fully reverted


def test_ema_apply_restore():
    m, x = _setup()
    ema = ExponentialMovingAverage(m.parameters(), decay=0.5)
    ema.update()
    live = np.asarray(m.weight._value).copy()
    m.weight.set_value(paddle.to_tensor(live + 1.0))
    ema.update()
    # shadow = 0.5*live + 0.5*(live+1) = live + 0.5
    ema.apply()
    np.testing.assert_allclose(
        np.asarray(m.weight._value), live + 0.5, rtol=1e-5)
    ema.restore()
    np.testing.assert_allclose(
        np.asarray(m.weight._value), live + 1.0, rtol=1e-6)


def test_model_average_running_mean():
    m, x = _setup()
    ma = ModelAverage(parameters=m.parameters())
    vals = []
    for i in range(3):
        m.weight.set_value(
            paddle.to_tensor(np.full((4, 4), float(i), "f4")))
        ma.step()
        vals.append(float(i))
    ma.apply()
    np.testing.assert_allclose(
        np.asarray(m.weight._value), np.mean(vals), rtol=1e-5)
    ma.restore()
    np.testing.assert_allclose(np.asarray(m.weight._value), 2.0)


def test_lookahead_syncs_master_weights():
    m, x = _setup()
    inner = paddle.optimizer.SGD(
        0.0, parameters=m.parameters(), multi_precision=True)
    # force master-state creation with one step
    loss = ((m(x) - x) ** 2).mean()
    loss.backward()
    opt = LookAhead(inner, alpha=0.0, k=1)
    w0 = np.asarray(m.weight._value).copy()
    opt.step()  # alpha=0 → snap back to slow (w0), incl. master
    st = inner._states.get(id(m.weight))
    if st is not None and "master" in st:
        np.testing.assert_allclose(
            np.asarray(st["master"]), w0, rtol=1e-6)


def test_model_average_requires_parameters():
    with pytest.raises(ValueError, match="parameters"):
        ModelAverage(0.15)


def test_lookahead_none_parameters_noop():
    opt = LookAhead(paddle.optimizer.SGD(0.1), k=1)
    opt.step()  # must not raise
