"""Launch controller (multi-proc, log aggregation, fail-fast) and the
VisualDL writer/callback (SURVEY.md §5 observability + launcher rows)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.visualdl import LogWriter, LogReader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The multi-PROCESS worker tests need cross-process XLA collectives,
# which this container's jax CPU backend does not implement (workers
# die with "... aren't implemented on the CPU backend"). The
# single-process 8-virtual-device tests cover the collective paths.
_needs_multiproc_collectives = pytest.mark.skip(
    reason="cross-process collectives unimplemented on the jax CPU "
           "backend in this container")


def _launch(tmp_path, script_body, extra_args, env_extra=None, timeout=120):
    script = tmp_path / "worker.py"
    script.write_text(script_body)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch", *extra_args,
         str(script)],
        env=env, capture_output=True, timeout=timeout,
    )


def test_launch_multiproc_env_and_log_aggregation(tmp_path):
    body = (
        "import os\n"
        "print('hello rank', os.environ['PADDLE_TRAINER_ID'],\n"
        "      'of', os.environ['PADDLE_TRAINERS_NUM'],\n"
        "      'local', os.environ['PADDLE_LOCAL_RANK'])\n"
    )
    logdir = tmp_path / "logs"
    r = _launch(tmp_path, body,
                ["--nproc_per_node", "2", "--log_dir", str(logdir)])
    assert r.returncode == 0, r.stderr
    out = r.stdout.decode()
    assert "[rank 0] hello rank 0 of 2 local 0" in out
    assert "[rank 1] hello rank 1 of 2 local 1" in out
    # per-rank files exist and carry the same lines
    assert "hello rank 0" in (logdir / "worker.0.log").read_text()
    assert "hello rank 1" in (logdir / "worker.1.log").read_text()


def test_launch_fail_fast_on_worker_error(tmp_path):
    body = (
        "import os, sys, time\n"
        "if os.environ['PADDLE_TRAINER_ID'] == '1':\n"
        "    sys.exit(3)\n"
        "time.sleep(30)\n"  # must be killed, not waited for
    )
    r = _launch(tmp_path, body, ["--nproc_per_node", "2"])
    assert r.returncode == 3
    assert b"terminating remaining workers" in r.stderr


def test_logwriter_scalars_roundtrip(tmp_path):
    logdir = str(tmp_path / "vdl")
    with LogWriter(logdir=logdir) as w:
        for i in range(5):
            w.add_scalar("loss", 1.0 / (i + 1), i)
        w.add_histogram("grads", np.random.randn(100), 0)
        w.add_text("note", "hello", 0)
        w.add_hparams({"lr": 0.1}, ["loss"])
    reader = LogReader(logdir)
    series = reader.scalars("loss")
    assert [s for s, _ in series] == list(range(5))
    assert series[0][1] == 1.0
    assert "loss" in reader.tags()


def test_visualdl_callback_with_hapi_fit(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.io import Dataset

    class Data(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.randn(32, 8).astype("f4")
            self.y = (np.abs(self.x.sum(1)) % 2).astype("i8")

        def __len__(self):
            return 32

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    model.prepare(
        paddle.optimizer.Adam(1e-2, parameters=net.parameters()),
        nn.CrossEntropyLoss(),
    )
    logdir = str(tmp_path / "vdl_cb")
    cb = paddle.callbacks.VisualDL(log_dir=logdir)
    model.fit(Data(), batch_size=8, epochs=2, verbose=0, callbacks=[cb])
    reader = LogReader(logdir)
    assert any(t.startswith("train") for t in reader.tags())
    assert len(reader.scalars("train/loss")) > 0


@pytest.mark.slow  # ~12s of deliberate SIGTERM-grace/kill waiting;
# the other launcher tests keep spawn/rendezvous covered in tier-1 —
# the 870s ceiling forced a re-tier as the suite grew (PR 7)
def test_launch_kills_sigterm_trapping_worker(tmp_path):
    """Fail-fast must escalate to SIGKILL when a worker traps SIGTERM."""
    body = (
        "import os, signal, sys, time\n"
        "signal.signal(signal.SIGTERM, lambda *a: None)  # trap + ignore\n"
        "if os.environ['PADDLE_TRAINER_ID'] == '0':\n"
        "    sys.exit(7)\n"
        "time.sleep(120)\n"
    )
    import time as _time

    t0 = _time.monotonic()
    r = _launch(tmp_path, body, ["--nproc_per_node", "2"])
    assert r.returncode == 7
    assert _time.monotonic() - t0 < 60  # escalation, not a 120s hang
    assert b"killing" in r.stderr


def test_histogram_empty_input_ok(tmp_path):
    with LogWriter(logdir=str(tmp_path / "v")) as w:
        w.add_histogram("empty", [], 0)  # must not raise


@_needs_multiproc_collectives
def test_two_process_rendezvous_and_collective(tmp_path):
    """Round-2 verdict item 7: a REAL 2-process localhost rendezvous —
    jax.distributed.initialize via init_parallel_env inside launched
    workers — followed by genuine cross-process collectives (values
    differ per rank; the results prove data crossed the process
    boundary)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    body = (
        "import numpy as np\n"
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.distributed as dist\n"
        "dist.init_parallel_env()\n"
        "import jax\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "rank = dist.get_rank()\n"
        "x = paddle.to_tensor(np.asarray([float(rank + 1)], 'f4'))\n"
        "dist.all_reduce(x)\n"
        "print('ALLREDUCE', rank, float(np.asarray(x._value)[0]))\n"
        "b = paddle.to_tensor(np.asarray([float((rank + 1) * 10)], 'f4'))\n"
        "dist.broadcast(b, src=1)\n"
        "print('BCAST', rank, float(np.asarray(b._value)[0]))\n"
        "outs = []\n"
        "g = paddle.to_tensor(np.asarray([float(rank)], 'f4'))\n"
        "dist.all_gather(outs, g)\n"
        "print('GATHER', rank, [float(np.asarray(t._value)[0]) for t in outs])\n"
        "p = paddle.to_tensor(np.asarray([2.0, 3.0], 'f4') + rank)\n"
        "dist.all_reduce(p, op=dist.ReduceOp.PROD)\n"
        "print('PROD', rank, [float(v) for v in np.asarray(p._value)])\n"
        "a = paddle.to_tensor(np.asarray([float((rank + 1) * 4)], 'f4'))\n"
        "dist.all_reduce(a, op=dist.ReduceOp.AVG)\n"
        "print('AVG', rank, float(np.asarray(a._value)[0]))\n"
        # reduce: only dst=1 keeps the sum; rank0 keeps its original
        "r = paddle.to_tensor(np.asarray([float(rank + 1)], 'f4'))\n"
        "dist.reduce(r, dst=1)\n"
        "print('REDUCE', rank, float(np.asarray(r._value)[0]))\n"
        # all_to_all: rank q's out[p] = rank p's in[q]
        "ins = [paddle.to_tensor(np.asarray([float(10 * rank + p)], 'f4'))\n"
        "       for p in range(2)]\n"
        "outs2 = []\n"
        "dist.all_to_all(outs2, ins)\n"
        "print('A2A', rank,"
        " [float(np.asarray(t._value)[0]) for t in outs2])\n"
        # scatter from src=0: rank p receives tensor_list[p]
        "s = paddle.to_tensor(np.asarray([0.0], 'f4'))\n"
        "sl = ([paddle.to_tensor(np.asarray([float(100 + p)], 'f4'))\n"
        "       for p in range(2)] if rank == 0 else None)\n"
        "dist.scatter(s, sl, src=0)\n"
        "print('SCATTER', rank, float(np.asarray(s._value)[0]))\n"
        # gather to dst=1: only rank1's list is filled
        "gl = []\n"
        "gt = paddle.to_tensor(np.asarray([float(7 * (rank + 1))], 'f4'))\n"
        "dist.gather(gt, gl, dst=1)\n"
        "print('GATHERDST', rank,"
        " [float(np.asarray(t._value)[0]) for t in gl])\n"
        # all_gather_object: arbitrary picklables of unequal size
        "objs = []\n"
        "dist.all_gather_object(objs, {'rank': rank, 'pad': 'x' * (rank * 50)})\n"
        "print('OBJ', rank, [o['rank'] for o in objs],"
        " [len(o['pad']) for o in objs])\n"
        # broadcast/scatter of arbitrary objects
        "bl = [{'cfg': 7, 'tag': 'fromzero'}] if rank == 0 else [None]\n"
        "dist.broadcast_object_list(bl, src=0)\n"
        "print('BOBJ', rank, bl[0]['cfg'], bl[0]['tag'])\n"
        "so = []\n"
        "dist.scatter_object_list(so, ['r0gets', 'r1gets'] if rank == 0\n"
        "                         else None, src=0)\n"
        "print('SOBJ', rank, so[0])\n"
        # p2p send/recv: the 2-process pair rides the collective
        "pt = paddle.to_tensor(np.asarray([41.0 + rank], 'f4'))\n"
        "if rank == 0:\n"
        "    dist.send(pt, dst=1)\n"
        "    print('SENT', rank)\n"
        "else:\n"
        "    dist.recv(pt, src=0)\n"
        "    print('RECV', rank, float(np.asarray(pt._value)[0]))\n"
        # round-5 subgroup semantics: a singleton group on rank1 — the
        # member reduces over the sub-mesh (sum over itself), the
        # non-member's tensor/list stay untouched
        "sg = dist.new_group(ranks=[1])\n"
        "sx = paddle.to_tensor(np.asarray([float(5 * (rank + 1))], 'f4'))\n"
        "dist.all_reduce(sx, group=sg)\n"
        "print('SUBAR', rank, float(np.asarray(sx._value)[0]))\n"
        "sl2 = []\n"
        "sgt = paddle.to_tensor(np.asarray([float(rank + 30)], 'f4'))\n"
        "dist.all_gather(sl2, sgt, group=sg)\n"
        "print('SUBAG', rank, [float(np.asarray(t._value)[0]) for t in sl2])\n"
        # src outside the group must refuse on every caller
        "try:\n"
        "    dist.broadcast(sx, src=0, group=sg)\n"
        "    print('SUBBC', rank, 'noraise')\n"
        "except ValueError:\n"
        "    print('SUBBC', rank, 'raised')\n"
        # collectives without a sub-mesh implementation refuse loudly
        "try:\n"
        "    dist.scatter(sx, None, src=1, group=sg)\n"
        "    print('SUBSC', rank, 'noraise')\n"
        "except NotImplementedError:\n"
        "    print('SUBSC', rank, 'raised')\n"
    )
    try:
        r = _launch(tmp_path, body,
                    ["--nproc_per_node", "2",
                     "--master", f"127.0.0.1:{port}"])
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"2-process rendezvous not runnable here: {e}")
    out = r.stdout.decode()
    assert r.returncode == 0, (out, r.stderr.decode()[-2000:])
    # rank0 contributed 1.0, rank1 2.0 → both see 3.0
    assert "ALLREDUCE 0 3.0" in out and "ALLREDUCE 1 3.0" in out
    # broadcast from rank1 (20.0) must overwrite rank0's 10.0
    assert "BCAST 0 20.0" in out and "BCAST 1 20.0" in out
    assert "GATHER 0 [0.0, 1.0]" in out and "GATHER 1 [0.0, 1.0]" in out
    # PROD elementwise across ranks: [2,3] * [3,4] = [6, 12] (shape kept)
    assert "PROD 0 [6.0, 12.0]" in out and "PROD 1 [6.0, 12.0]" in out
    # AVG: (4 + 8) / 2
    assert "AVG 0 6.0" in out and "AVG 1 6.0" in out
    # reduce dst=1: rank0 keeps its original 1.0, rank1 gets 1+2=3
    assert "REDUCE 0 1.0" in out and "REDUCE 1 3.0" in out
    # all_to_all: rank0 in=[0,1] rank1 in=[10,11] → rank0 out=[0,10],
    # rank1 out=[1,11]
    assert "A2A 0 [0.0, 10.0]" in out and "A2A 1 [1.0, 11.0]" in out
    # scatter from rank0's [100, 101]
    assert "SCATTER 0 100.0" in out and "SCATTER 1 101.0" in out
    # gather to dst=1: rank0's list stays empty
    assert "GATHERDST 0 []" in out
    assert "GATHERDST 1 [7.0, 14.0]" in out
    # all_gather_object with unequal pickled sizes
    assert "OBJ 0 [0, 1] [0, 50]" in out and "OBJ 1 [0, 1] [0, 50]" in out
    # object broadcast/scatter
    assert "BOBJ 0 7 fromzero" in out and "BOBJ 1 7 fromzero" in out
    assert "SOBJ 0 r0gets" in out and "SOBJ 1 r1gets" in out
    # p2p: rank1 received rank0's 41.0 (its own value was 42.0)
    assert "SENT 0" in out and "RECV 1 41.0" in out
    # subgroup: member (rank1) reduced over the singleton sub-mesh
    # (10.0 = its own value), non-member untouched (5.0)
    assert "SUBAR 0 5.0" in out and "SUBAR 1 10.0" in out
    assert "SUBAG 0 []" in out and "SUBAG 1 [31.0]" in out
    assert "SUBBC 0 raised" in out and "SUBBC 1 raised" in out
    assert "SUBSC 0 raised" in out and "SUBSC 1 raised" in out


@_needs_multiproc_collectives
def test_three_process_two_member_subgroup(tmp_path):
    """Round-5 subgroup semantics, the real case: a 2-member sub-mesh in
    a 3-process job — the members' collective must coordinate ACROSS a
    process boundary while the third process skips it entirely, and a
    fleet-style mesh_axis group must keep world semantics (its ranks are
    device positions, not process ids)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    body = (
        "import numpy as np\n"
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.distributed as dist\n"
        "dist.init_parallel_env()\n"
        "import jax\n"
        "assert jax.process_count() == 3, jax.process_count()\n"
        "rank = dist.get_rank()\n"
        # unsorted on purpose: new_group sorts → members [0, 2]
        "sg = dist.new_group(ranks=[2, 0])\n"
        "assert sg.ranks == [0, 2], sg.ranks\n"
        "x = paddle.to_tensor(np.asarray([float(rank + 1)], 'f4'))\n"
        "dist.all_reduce(x, group=sg)\n"
        "print('SG3AR', rank, float(np.asarray(x._value)[0]))\n"
        "outs = []\n"
        "g = paddle.to_tensor(np.asarray([float(100 + rank)], 'f4'))\n"
        "dist.all_gather(outs, g, group=sg)\n"
        "print('SG3AG', rank, [float(np.asarray(t._value)[0]) for t in outs])\n"
        # broadcast from the higher member crosses the sub-mesh
        "b = paddle.to_tensor(np.asarray([float((rank + 1) * 10)], 'f4'))\n"
        "dist.broadcast(b, src=2, group=sg)\n"
        "print('SG3BC', rank, float(np.asarray(b._value)[0]))\n"
        # mesh_axis groups are chip-level handles: world semantics kept
        "mg = dist.new_group(ranks=[0, 1], mesh_axis='mp')\n"
        "w = paddle.to_tensor(np.asarray([1.0], 'f4'))\n"
        "dist.all_reduce(w, group=mg)\n"
        "print('SG3MA', rank, float(np.asarray(w._value)[0]))\n"
    )
    try:
        r = _launch(tmp_path, body,
                    ["--nproc_per_node", "3",
                     "--master", f"127.0.0.1:{port}"])
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"3-process rendezvous not runnable here: {e}")
    out = r.stdout.decode()
    assert r.returncode == 0, (out, r.stderr.decode()[-2000:])
    # members 0 and 2 reduce 1+3=4 across the process boundary; rank 1
    # (non-member) keeps its 2.0
    assert "SG3AR 0 4.0" in out and "SG3AR 2 4.0" in out
    assert "SG3AR 1 2.0" in out
    # gather rows in sorted-global-rank order; non-member list untouched
    assert "SG3AG 0 [100.0, 102.0]" in out
    assert "SG3AG 2 [100.0, 102.0]" in out
    assert "SG3AG 1 []" in out
    # broadcast from member 2: member 0 overwritten, rank 1 untouched
    assert "SG3BC 0 30.0" in out and "SG3BC 2 30.0" in out
    assert "SG3BC 1 20.0" in out
    # mesh_axis group → world semantics: all 3 processes summed
    assert "SG3MA 0 3.0" in out and "SG3MA 1 3.0" in out \
        and "SG3MA 2 3.0" in out


def test_two_process_rpc(tmp_path):
    """Round-3 verdict missing #4: REAL cross-process rpc — two launched
    workers, rank0 calls a function that executes ON rank1 (proved by
    reading the callee's env), sync + async + remote-exception paths."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    body = (
        "import os\n"
        "import paddle_tpu.distributed.rpc as rpc\n"
        "def my_rank(x):\n"
        "    return int(os.environ['PADDLE_TRAINER_ID']) * 100 + x\n"
        "def boom():\n"
        "    raise ValueError('remote-boom')\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        f"rpc.init_rpc(f'worker{{rank}}', rank, 2, '127.0.0.1:{port}')\n"
        "infos = rpc.get_all_worker_infos()\n"
        "print('INFOS', rank, sorted(w.name for w in infos))\n"
        "if rank == 0:\n"
        "    print('SYNC', rpc.rpc_sync('worker1', my_rank, args=(7,)))\n"
        "    fut = rpc.rpc_async('worker1', my_rank, args=(8,))\n"
        "    print('ASYNC', fut.result())\n"
        "    try:\n"
        "        rpc.rpc_sync('worker1', boom)\n"
        "    except ValueError as e:\n"
        "        print('REMOTE_ERR', e)\n"
        "    print('LOCAL', rpc.rpc_sync('worker0', my_rank, args=(9,)))\n"
        # no sleep: shutdown() is collective — rank1 keeps serving until
        # rank0 deregisters
        "rpc.shutdown()\n"
    )
    r = _launch(tmp_path, body, ["--nproc_per_node", "2"])
    out = r.stdout.decode()
    assert r.returncode == 0, (out, r.stderr.decode()[-2000:])
    assert "INFOS 0 ['worker0', 'worker1']" in out
    assert "INFOS 1 ['worker0', 'worker1']" in out
    # 107: executed on rank1 (1*100 + 7), not locally
    assert "SYNC 107" in out
    assert "ASYNC 108" in out
    assert "REMOTE_ERR remote-boom" in out
    assert "LOCAL 9" in out


@_needs_multiproc_collectives
def test_two_process_spmd_hybrid_training(tmp_path):
    """MULTI-HOST SPMD training e2e (round 4): two launched controller
    processes, 2 local CPU devices each -> one 4-device global mesh,
    dp2 x mp2 hybrid TP training through fleet.init + JittedTrainStep.
    Oracle: losses equal the mesh-less serial run of the same step, on
    BOTH ranks, across steps (numerics prove the cross-process mesh is
    real and correct)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    body = (
        "import numpy as np\n"
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.distributed as dist\n"
        "dist.init_parallel_env()\n"
        "import jax\n"
        "assert len(jax.devices()) == 4 and len(jax.local_devices()) == 2\n"
        "from paddle_tpu.distributed import fleet\n"
        "from paddle_tpu.nlp import (LlamaConfig, LlamaForCausalLM,\n"
        "                            LlamaPretrainingCriterion)\n"
        "from paddle_tpu.jit.train import JittedTrainStep\n"
        "strategy = fleet.DistributedStrategy()\n"
        "strategy.hybrid_configs = {'dp_degree': 2, 'mp_degree': 2,\n"
        "                           'pp_degree': 1, 'sharding_degree': 1}\n"
        "fleet.init(is_collective=True, strategy=strategy)\n"
        "paddle.seed(0)\n"
        "cfg = LlamaConfig.tiny(tensor_parallel=True)\n"
        "model = LlamaForCausalLM(cfg)\n"
        "crit = LlamaPretrainingCriterion()\n"
        "opt = paddle.optimizer.AdamW(1e-3,\n"
        "    parameters=model.parameters(), weight_decay=0.01)\n"
        "step = JittedTrainStep(model, lambda o, l: crit(o, l), opt)\n"
        "ids = paddle.to_tensor(\n"
        "    np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 32)))\n"
        "rank = dist.get_rank()\n"
        "for i in range(3):\n"
        "    print('LOSS', rank, i, float(step(ids, ids)))\n"
    )
    try:
        r = _launch(
            tmp_path, body,
            ["--nproc_per_node", "2", "--master", f"127.0.0.1:{port}"],
            env_extra={
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
            timeout=180)
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"2-process rendezvous not runnable here: {e}")
    out = r.stdout.decode()
    assert r.returncode == 0, (out, r.stderr.decode()[-2000:])

    # serial oracle in THIS process: same seed/model/data, no mesh
    from paddle_tpu.parallel import mesh as mesh_state
    from paddle_tpu.nlp import (
        LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    )
    from paddle_tpu.jit.train import JittedTrainStep

    mesh_state.set_mesh(None)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=True)  # degrades serial
    model = LlamaForCausalLM(cfg)
    crit = LlamaPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters(),
                                 weight_decay=0.01)
    step = JittedTrainStep(model, lambda o, l: crit(o, l), opt)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 32)))
    import re

    got = {}  # (rank, step) -> loss
    for m in re.finditer(r"LOSS (\d) (\d) ([\d.eE+-]+)", out):
        got[(int(m.group(1)), int(m.group(2)))] = float(m.group(3))
    for i in range(3):
        want = float(step(ids, ids))
        for rank in (0, 1):
            assert (rank, i) in got, (rank, i, out)
            # reordered reductions in the partitioned graph → epsilon,
            # not string equality
            assert abs(got[(rank, i)] - want) < 5e-4 * max(1.0, abs(want)), (
                rank, i, got[(rank, i)], want)
