"""Pipeline-parallel overlap evidence (round-1 verdict item #5's "Done"
criterion: a pp bench showing overlap — step time per microbatch SHRINKS
as microbatches amortize the pipeline bubble).

Runs on the 8-device virtual CPU mesh with a compute-heavy stage stack
(big matmuls so compute dominates Python scheduling). Per-microbatch
step time falls with m for two reasons: (a) fixed per-step costs
(optimizer update, host scheduling) amortize, and (b) 1F1B overlap.
To isolate (b), a pp=1 control run measures pure overhead amortization
with no pipeline; overlap evidence is the pp=4 amortization EXCEEDING
the pp=1 control's.

    PYTHONPATH=. python scripts/bench_pp_overlap.py
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax

    # must run before any backend initialization
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.parallel import mesh as mesh_state
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineParallel,
    )

    D = 1024  # big matmuls: compute >> host scheduling
    descs = [LayerDesc(nn.Linear, D, D) for _ in range(8)]

    def run(acc_steps, pp_degree, iters=5, batch=32):
        mesh_state.set_mesh(None)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": pp_degree,
            "sharding_degree": 1,
        }
        strategy.pipeline_configs = {"accumulate_steps": acc_steps}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        pipe = PipelineLayer(layers=descs, num_stages=pp_degree,
                             loss_fn=nn.MSELoss())
        model = PipelineParallel(
            pipe, fleet.get_hybrid_communicate_group(), strategy)
        opt = paddle.optimizer.SGD(0.01, parameters=pipe.parameters())
        params = list(pipe.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(
                batch * acc_steps, D).astype("f4"))
        y = paddle.to_tensor(
            np.random.RandomState(1).randn(
                batch * acc_steps, D).astype("f4"))

        def step():
            model.train_batch([x, y], opt)
            # real device barrier: updated params, not the host-side loss
            jax.block_until_ready([p._value for p in params])

        step()  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            step()
        dt = (time.perf_counter() - t0) / iters
        mesh_state.set_mesh(None)
        return dt / acc_steps  # per-microbatch time

    # pp=1 control: amortization of fixed per-step costs WITHOUT overlap
    c1 = run(1, pp_degree=1)
    c8 = run(8, pp_degree=1)
    t1 = run(1, pp_degree=4)
    t8 = run(8, pp_degree=4)
    out = {
        "metric": "pp4_per_microbatch_step_time_ms",
        "pp4_m1_ms": round(t1 * 1000, 2),
        "pp4_m8_ms": round(t8 * 1000, 2),
        "pp4_amortization": round(t1 / t8, 2),
        "pp1_control_amortization": round(c1 / c8, 2),
        "overlap_beyond_overhead": round((t1 / t8) / (c1 / c8), 2),
        "ideal_1f1b_speedup": round((1 + 3) / (1 + 3 / 8), 2),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
