"""Pipeline-parallel overlap evidence (round-1 verdict item #5's "Done"
criterion: a pp bench showing overlap — step time per microbatch SHRINKS
as microbatches amortize the pipeline bubble).

Runs on the 8-device virtual CPU mesh with a compute-heavy stage stack
(big matmuls so compute dominates Python scheduling). For a 1F1B
schedule with S stages and m microbatches, ideal utilization is
m / (m + S - 1); with NO overlap (stages strictly serialized) the
per-microbatch time would be flat in m. We report per-microbatch step
time at m=1 vs m=8 — a falling curve is overlap.

    python scripts/bench_pp_overlap.py
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax

    # must run before any backend initialization
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.parallel import mesh as mesh_state
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineParallel,
    )

    D = 1024  # big matmuls: compute >> host scheduling
    descs = [LayerDesc(nn.Linear, D, D) for _ in range(8)]

    def run(acc_steps, iters=5, batch=32):
        mesh_state.set_mesh(None)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 4,
            "sharding_degree": 1,
        }
        strategy.pipeline_configs = {"accumulate_steps": acc_steps}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        pipe = PipelineLayer(layers=descs, num_stages=4,
                             loss_fn=nn.MSELoss())
        model = PipelineParallel(
            pipe, fleet.get_hybrid_communicate_group(), strategy)
        opt = paddle.optimizer.SGD(0.01, parameters=pipe.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(
                batch * acc_steps, D).astype("f4"))
        y = paddle.to_tensor(
            np.random.RandomState(1).randn(
                batch * acc_steps, D).astype("f4"))

        def step():
            loss = model.train_batch([x, y], opt)
            float(loss)  # block

        step()  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            step()
        dt = (time.perf_counter() - t0) / iters
        mesh_state.set_mesh(None)
        return dt / acc_steps  # per-microbatch time

    t1 = run(1)
    t8 = run(8)
    out = {
        "metric": "pp4_per_microbatch_step_time_ms",
        "m1_ms": round(t1 * 1000, 2),
        "m8_ms": round(t8 * 1000, 2),
        "overlap_speedup": round(t1 / t8, 2),
        "ideal_1f1b_speedup": round((1 + 3) / (1 + 3 / 8), 2),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
