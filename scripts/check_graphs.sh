#!/usr/bin/env bash
# Pre-merge static gate: tracer-hazard lint + graph-budget audit +
# golden-fingerprint compare over every registered recipe. Exits
# non-zero on any hazard, budget violation, stale allowlist entry, or
# fingerprint drift. Run from anywhere; ~1 min on the CPU backend.
#
#     scripts/check_graphs.sh
#
# After an INTENTIONAL graph change: regenerate the goldens with
# `python -m paddle_tpu.analysis --update-goldens`, review their git
# diff, and re-run this gate.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}."

python -m paddle_tpu.analysis.lint paddle_tpu/ scripts/ tests/
python -m paddle_tpu.analysis --check --fingerprint --cost
# Observability gate (ISSUE 5 + 6): rebuild the serving + speculative
# recipes — whose engines run with FULL instrumentation (metrics
# registry + request tracer + SLOs + flight recorder) — and assert
# budgets (0 host callbacks, donation) and golden fingerprints are
# UNCHANGED, i.e. the obs layer provably never touches the compiled
# quantum. Also asserts the instrumentation actually recorded (metrics
# counted, trace validates), then runs the SLO-evaluation smoke on the
# demo engine: lenient objectives read ok, impossible ones critical,
# and every forced threshold crossing dumps a schema-valid flight
# journal.
#
# Front-door gate (ISSUE 7): the `--check --fingerprint` pass above
# also audits `serving_frontdoor_step` (the per-request-sampling
# quantum variant built through the full policy tier after a forced
# preemption: 0 host callbacks, pools donated, its own golden), and
# `obs check` runs the front-door smoke — a forced priority preemption
# must fire the preempted/resumed/recomputed counters, resume must
# continue the stream, drain must flush the flight journals, and the
# watch dashboard must render the overload line. H106/H107 lint covers
# serving/{frontend,policy}.py through the repo-wide scan above.
#
# Prefix-cache gate (ISSUE 9): `--check --fingerprint` audits
# `serving_prefix_step` (the prefix_cache=True engine's quantum after
# a REAL cache hit + copy-on-write: 0 host callbacks, pools donated,
# same caps as serving_decode_step — the proof the whole
# content-addressed cache policy is host-side allocator work), and
# `obs check` runs the prefix smoke: forced hit/COW must fire the
# serving_prefix_cache_* counters, streams must stay bit-identical to
# an unshared engine, and the dashboard must render the prefix line.
# TP-serving gate (ISSUE 11): `--check --fingerprint` above also
# audits `serving_tp_step` — the tp=2 quantum on the ("mp",) mesh:
# params head/ffn-sharded through the training recipes' mp layers, KV
# pool leaves split along kv heads, still ONE dispatch with in-graph
# collectives. Its budget pins the collective census (<=8 ops /
# <=46 KB per quantum), demands the pool leaves CARRY the mp axis
# (min_sharded_params=4, max_replicated_param_bytes=0) and keeps 0
# host callbacks + donation; the tp=1 recipes' goldens must stay
# byte-identical (the mesh enters only through the tp recipe). The
# CLI re-execs with 8 virtual CPU devices when the host exposes fewer.
#
# Resilience gate (ISSUE 13): the recipe engines above now carry a
# DISARMED FaultInjector (faults.py threads every host boundary), so
# the `--check --fingerprint` pass doubles as the proof that the
# fault-injection seams change no compiled graph: 0 host callbacks
# and byte-identical goldens with the injector present. `obs check`
# then runs the bounded chaos-soak smoke (~30 s): a seeded
# faults x preemption x COW run where every non-poisoned stream must
# stay bit-exact vs the fault-free arm and the pools must drain to
# zero leaked blocks; the full 200-round soak lives in
# tests/test_resilience.py (slow) and scripts/soak.py.
#
# Quantized-serving gate (ISSUE 14): `--check --fingerprint` above
# also audits `serving_int8_step` — the weight-only-int8 + int8-KV
# decode quantum. Its budget demands quantization is LIVE in the
# compiled graph (min_int8_matmuls=10 contractions fed from int8
# storage; a silently-disabled quant path would stream bit-identical
# tokens but blows this floor), keeps 0 host callbacks + full pool
# donation, and pins temp/peak bytes (~613 KB / ~286 KB audited).
# Every float recipe's golden must stay byte-identical — the KV scale
# pools ride the quantum signature as EMPTY pytrees when unquantized,
# so the float graphs never see them. `obs check` then runs the int8
# smoke: a forced prefix hit + COW on an int8 pool whose streams are
# bit-identical to the unshared int8 engine, a >=2x pool-residency
# win over the float twin, and the dtype-labeled serving_pool_bytes
# gauge live in the registry.
#
# Cost-model gate (ISSUE 16): the lint scan above now covers tests/
# and the host-escape rules H108-H110 (implicit device->host syncs in
# HOST code: bare .item(), float()/np.* over jax values,
# block_until_ready outside bench/test paths) with a justified-only
# allowlist; `--cost` prints each recipe's FLOP/byte counts, roofline
# placement and device-time floor on the default chip, and gates that
# BOTH cost sources (XLA cost_analysis + the jaxpr walker) populated
# and agree within the pinned band. The per-recipe FLOP/byte/intensity
# caps ride `--check`; the exact counts ride the goldens; the
# cross-source ratio is also budget-guarded in BENCH_COST_r17.json.
#
# Multi-quantum gate (ISSUE 17): `--check --fingerprint` above also
# audits `serving_multiquantum_step` — the K=4 on-device decode driver
# (lax.while_loop over the scanned quantum, retiring rows against the
# eos/max-len masks WITHOUT re-entering the host) with the fused
# online-softmax paged-attention inner loop. Its budget keeps 0 host
# callbacks + full pool donation and pins the fused path's structural
# win: temp bytes <=12 KB per dispatch (the gather path audits
# ~207 KB — the w*bs-wide gathered K/V staging the fused loop elides).
# The single-quantum recipes' goldens must stay byte-identical: K=1
# engines build the exact same scanned quantum, and the XLA-gather
# attention stays the default parity oracle. Note the jaxpr-walker
# HBM cap is loose (13 MB/token): the walker charges the block-scan's
# gathered operands once PER BLOCK STEP while XLA's compiled report
# reads ~717 KB/dispatch; the flops agreement band still gates.
#
# Cluster gate (ISSUE 15): the router is pure host code riding the
# same engines, so `--check --fingerprint` above (0 host callbacks,
# byte-identical goldens) already proves the cluster tier touches no
# compiled graph. `obs check` then runs the cluster smoke: a
# 2-replica ClusterFrontDoor on a shared-prefix trace must re-land
# twin prompts on their prefix owner (affinity hits live in the
# serving_router_* counters), stream bit-identical to a cluster-of-1
# run, and render the merged ClusterExporter dashboard's cluster line.
python -m paddle_tpu.obs check
# Perf sentinel (ISSUE 10): the runtime twin of the graph gate —
# validate/index the BENCH_*.json trajectory and enforce the declared
# PerfBudget bands (spec >=1.1x, shed-arm p95 bound >=1.5x, prefix
# prefill-token ratio >=2x, tp per-chip pool residency 2.0x,
# obs/SLO/attribution overhead <3%, ...).
scripts/check_perf.sh
echo "check_graphs: lint + budgets + fingerprints (+obs +perf) all green"
