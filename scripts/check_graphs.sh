#!/usr/bin/env bash
# Pre-merge static gate: tracer-hazard lint + graph-budget audit +
# golden-fingerprint compare over every registered recipe. Exits
# non-zero on any hazard, budget violation, stale allowlist entry, or
# fingerprint drift. Run from anywhere; ~1 min on the CPU backend.
#
#     scripts/check_graphs.sh
#
# After an INTENTIONAL graph change: regenerate the goldens with
# `python -m paddle_tpu.analysis --update-goldens`, review their git
# diff, and re-run this gate.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}."

python -m paddle_tpu.analysis.lint paddle_tpu/ scripts/
python -m paddle_tpu.analysis --check --fingerprint
echo "check_graphs: lint + budgets + fingerprints all green"
