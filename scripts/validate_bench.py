"""Validate + index the bench trajectory, then enforce the perf
budgets (the runtime half of the merge gate; the graph half is
``python -m paddle_tpu.analysis --check --fingerprint``).

    python scripts/validate_bench.py --check     # the gate (CI)
    python scripts/validate_bench.py --update    # regenerate BENCH_INDEX.json
    python scripts/validate_bench.py             # report only

``--check`` regenerates the index in memory from every BENCH_*.json /
MULTICHIP_*.json in the repo root, fails on (a) schema drift in any
artifact, (b) a stale/missing checked-in BENCH_INDEX.json, and (c) any
guarded ratio outside its declared band — each failure as a readable
field-level diff line. After intentionally re-running a bench or
moving a band (see README "performance sentinel" for the honest
protocol), run ``--update`` and review the BENCH_INDEX.json diff like
a golden.

The perf_budget module is loaded by file path on purpose: the sentinel
is pure stdlib and must not pay (or depend on) the jax import that
``import paddle_tpu`` triggers — this script runs in ~100ms anywhere.
"""
import importlib.util
import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
INDEX_PATH = os.path.join(ROOT, "BENCH_INDEX.json")

_spec = importlib.util.spec_from_file_location(
    "_perf_budget", os.path.join(ROOT, "paddle_tpu", "analysis",
                                 "perf_budget.py"))
pb = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(pb)


def artifact_paths():
    paths = [p for p in glob.glob(os.path.join(ROOT, "BENCH_*.json"))
             if os.path.basename(p) != "BENCH_INDEX.json"]
    paths += glob.glob(os.path.join(ROOT, "MULTICHIP_*.json"))
    return sorted(paths, key=os.path.basename)


def render_index(index):
    return json.dumps(index, indent=1, sort_keys=True) + "\n"


def fail(lines, header):
    print(f"validate_bench: FAIL — {header}", file=sys.stderr)
    for ln in lines:
        print(f"  - {ln}", file=sys.stderr)
    return 1


def main(argv):
    update = "--update" in argv
    check = "--check" in argv
    budgets = pb.default_perf_budgets()
    try:
        index = pb.build_index(artifact_paths(), budgets=budgets)
    except ValueError as e:
        return fail([str(e)], "artifact schema drift")

    if update:
        with open(INDEX_PATH, "w") as f:
            f.write(render_index(index))
        print(f"validate_bench: wrote {os.path.basename(INDEX_PATH)} "
              f"({len(index['artifacts'])} artifacts, "
              f"{len(index['guarded'])} guarded budgets)")
    elif check:
        if not os.path.exists(INDEX_PATH):
            return fail(
                ["BENCH_INDEX.json missing — run "
                 "scripts/validate_bench.py --update and commit it"],
                "no checked-in index")
        with open(INDEX_PATH) as f:
            checked_in = json.load(f)
        diffs = pb.compare_index(index, checked_in)
        if diffs:
            diffs.append("after an INTENTIONAL bench re-run: "
                         "scripts/validate_bench.py --update, review "
                         "the BENCH_INDEX.json diff, commit")
            return fail(diffs, "BENCH_INDEX.json stale")

    try:
        ok_lines = pb.check_perf(index, budgets)
    except pb.PerfBudgetViolation as e:
        return fail(e.violations, "perf budget violation(s)")
    for ln in ok_lines:
        print(f"  {ln}")
    print(f"validate_bench: {len(index['artifacts'])} artifacts "
          f"indexed, {len(budgets)} budgets green")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
