"""Section decomposition of the 7B-shape train step at B1/B2 (round-5
B2-cliff investigation): times fwd-only and fwd+bwd as separate
chained-fori_loop programs with a scalar fetch barrier and N-vs-2N
differencing (BENCH_NOTES methodology), to locate where the B2 MFU gap
lives. The full-step time comes from bench_7b_sweep.py.
"""
from __future__ import annotations

import sys
import time

sys.path.insert(0, "/root/repo")


def timed(fn, n_lo=3, reps=3):
    """min over reps of (t(2n) - t(n)) / n, warm-compiled first; n varies
    per rep so no dispatch is byte-identical (the axon cache would serve
    a repeat without executing)."""
    import jax

    float(jax.device_get(fn(1)))  # compile + warm
    best = None
    for r in range(reps):
        n = n_lo + r
        ts = {}
        for m in (n, 2 * n):
            t0 = time.perf_counter()
            out = fn(m)
            float(jax.device_get(out))
            ts[m] = time.perf_counter() - t0
        per = (ts[2 * n] - ts[n]) / n
        best = per if best is None else min(best, per)
    return best


def main(batch, fused):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle  # noqa: F401
    from paddle_tpu.nlp import LlamaConfig
    from bench import build_step

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=4, num_attention_heads=32,
        max_position_embeddings=4096, tensor_parallel=False,
        fuse_linear_cross_entropy=bool(fused),
    )
    cfg.lce_chunk_rows = 2048
    model, step, ids = build_step(cfg, batch, 4096, moment_dtype="bfloat16")
    ids_v = ids._value
    p_vals, b_vals = step._p_vals, step._b_vals
    criterion = step._criterion

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.core import autograd
    from paddle_tpu.core.random import next_key, traced_key_scope
    from paddle_tpu.jit import functional_call

    def loss_of(pv, rng):
        with autograd.no_grad(), traced_key_scope(rng):
            def fwd_and_loss(xt, yt):
                return criterion(model(xt), yt)

            out_t, _ = functional_call(
                model, fwd_and_loss,
                [Tensor(ids_v, stop_gradient=True),
                 Tensor(ids_v, stop_gradient=True)], {}, pv, b_vals)
        return out_t._value

    rng0 = next_key()

    # params must be jit ARGUMENTS — closed-over they become program
    # constants and the axon tunnel uploads all ~10 GB per compile
    # iterations must be DATA-DEPENDENT or XLA hoists the loop-invariant
    # body and the loop times as free: thread acc into a param via a
    # numerically-negligible perturbation
    def chain(pv, acc):
        return [pv[0] + (acc * jnp.float32(1e-38)).astype(pv[0].dtype)] \
            + list(pv[1:])

    @jax.jit
    def fwd_n(pv, n):
        def body(i, acc):
            return acc + loss_of(chain(pv, acc),
                                 jax.random.fold_in(rng0, i))

        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))

    @jax.jit
    def grad_n(pv, n):
        def body(i, acc):
            g = jax.grad(loss_of)(chain(pv, acc),
                                  jax.random.fold_in(rng0, i))
            # consume EVERY grad — fetching one would let XLA prune the
            # other params' dW matmuls from the backward
            return acc + sum(x.astype(jnp.float32).sum() for x in g)

        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))

    t_fwd = timed(lambda n: fwd_n(p_vals, n))
    print(f"B{batch} fused={int(bool(fused))}: fwd-only "
          f"{t_fwd*1e3:.1f} ms", flush=True)
    t_g = timed(lambda n: grad_n(p_vals, n))
    print(f"B{batch} fused={int(bool(fused))}: fwd+bwd "
          f"{t_g*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]), bool(int(sys.argv[2])))
