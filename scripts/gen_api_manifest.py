"""Regenerate the checked-in API manifests (tests/manifests/*.txt).

The manifests are the AUDITABLE form of COVERAGE.md's surface claims:
one name per line, asserted present-and-callable by
tests/test_api_manifest.py. Regenerate after intentionally extending the
surface; a missing name after a refactor is a test failure, not a silent
doc drift.

    PYTHONPATH=. JAX_PLATFORMS=cpu python scripts/gen_api_manifest.py
"""
import inspect
import os

import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "manifests")


def _callables(mod, exclude=()):
    return sorted(
        n for n in dir(mod)
        if not n.startswith("_") and n not in exclude
        and callable(getattr(mod, n))
        and not inspect.ismodule(getattr(mod, n)))


def main():
    os.makedirs(OUT, exist_ok=True)
    sets = {
        # paddle.* — ops, creation, autograd/device/dtype utilities
        "top_level.txt": _callables(paddle),
        "nn_functional.txt": _callables(paddle.nn.functional),
        "nn_layers.txt": _callables(paddle.nn),
        "linalg.txt": _callables(paddle.linalg),
        "fft.txt": _callables(paddle.fft),
        "sparse.txt": _callables(paddle.sparse),
        "incubate_functional.txt": _callables(
            paddle.incubate.nn.functional),
        "analysis.txt": _callables(
            __import__("paddle_tpu.analysis", fromlist=["analysis"])),
        "serving.txt": _callables(
            __import__("paddle_tpu.serving", fromlist=["serving"])),
        "obs.txt": _callables(
            __import__("paddle_tpu.obs", fromlist=["obs"])),
    }
    for fname, names in sets.items():
        path = os.path.join(OUT, fname)
        with open(path, "w") as f:
            f.write("\n".join(names) + "\n")
        print(f"{fname}: {len(names)}")
    # registry count for COVERAGE.md
    print(f"OP_REGISTRY: {len(paddle.OP_REGISTRY)}")


if __name__ == "__main__":
    main()
