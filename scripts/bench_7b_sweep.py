"""7B-shape batch/fusion sweep on the real chip — the B2 HBM-cliff
attack (round-5). Reuses bench.py's build_step/meter machinery.

Usage: python scripts/bench_7b_sweep.py B FUSED [SEQ] [REMAT]
  B: batch size; FUSED: 0|1 (fused lm-head+CE); SEQ: default 4096;
  REMAT: none|core_attn|mlp (default none)
Prints one JSON line per config.
"""
from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, "/root/repo")

from bench import build_step, count_params, log  # noqa: E402


def run(batch, fused, seq=4096, remat="none", iters=3, K=10):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig
    from paddle_tpu.profiler.mfu import (
        MFUMeter, transformer_train_flops,
    )

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=4, num_attention_heads=32,
        max_position_embeddings=max(4096, seq), tensor_parallel=False,
        use_recompute=remat != "none",
        recompute_granularity=remat if remat != "none" else "full",
        fuse_linear_cross_entropy=bool(fused),
    )
    import os

    cfg.lce_chunk_rows = int(os.environ.get("LCE_CHUNK", "1024"))
    model, step, ids = build_step(cfg, batch, seq,
                                  moment_dtype="bfloat16")
    n_params = count_params(model)
    tokens = batch * seq
    flops = transformer_train_flops(
        n_params, tokens, num_layers=cfg.num_hidden_layers,
        seq_len=seq, hidden=cfg.hidden_size, causal=True)
    ids_stacked = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (K, batch, seq)))
    t0 = time.perf_counter()
    meter = MFUMeter(flops * K, tokens * K)
    res = meter.measure(
        lambda: step.run_steps(ids_stacked, ids_stacked),
        warmup=1, iters=iters)
    res["step_time_s"] /= K
    log(f"B{batch} fused={fused} seq={seq} remat={remat}: "
        f"{time.perf_counter()-t0:.0f}s wall")
    out = {
        "config": f"B{batch}_S{seq}_fused{int(bool(fused))}_{remat}",
        "mfu_pct": round(res["mfu"] * 100, 2),
        "tok_s_chip": round(res["tokens_per_sec_per_chip"]),
        "step_ms": round(res["step_time_s"] * 1000, 1),
    }
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 2
    fused = bool(int(sys.argv[2])) if len(sys.argv) > 2 else True
    seq = int(sys.argv[3]) if len(sys.argv) > 3 else 4096
    remat = sys.argv[4] if len(sys.argv) > 4 else "none"
    run(b, fused, seq, remat)
