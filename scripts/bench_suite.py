"""Secondary benchmark suite: the BASELINE.md config table beyond the
headline (bench.py stays the driver's single-JSON-line contract).

Runs each config at a single-chip-feasible scale and prints one JSON
line per config; results are recorded in BENCH_NOTES.md.

    PYTHONPATH=. python scripts/bench_suite.py [config ...]

Configs: graph_audit | graph_fingerprint | cost_model |
resnet50_eager |
resnet50_jit | gpt2_jit | ernie_engine |
sd_unet | llama_decode | llama_941m_decode_int8 | llama_941m_train |
llama_941m_packed_train | llama_7b_shape_train |
llama_7b_shape_b2_train | llama_7b_shape_longctx | moe_dispatch |
serving_engine | speculative_decode | speculative_serving |
serving_obs_overhead | fault_recovery_overhead |
attribution_overhead | slo_overhead |
serving_overload |
shared_prefix | serving_tp | serving_int8 | serving_cluster |
dispatch_decomposition
(the 7B-shape Llama MFU headline also lives in bench.py; the suite row
keeps the fallback-variant detail, llama_941m_train tracks the
rounds-1..3 headline config, llama_941m_packed_train the ragged
packed-varlen path, llama_7b_shape_longctx the S=16k long-context row)
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _time_it(fn, warmup=2, iters=5):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def resnet50_eager():
    """Config #1: ResNet-50 eager train step, images/sec."""
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50()
    ce = paddle.nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    batch = 32
    x = paddle.to_tensor(rng.randn(batch, 3, 224, 224).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, 1000, batch).astype("i8"))

    def step():
        loss = ce(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.asarray(loss._value)  # block: same sync rule as the jit bench
        return loss

    step()  # compile ops
    dt = _time_it(step, warmup=1, iters=3)
    return {"metric": "resnet50_eager_images_per_sec",
            "value": round(batch / dt, 1), "unit": "img/s"}


def gpt2_jit():
    """Config #2: GPT-2 345M-class static-graph (jitted) train step."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    from paddle_tpu.jit.train import JittedTrainStep
    from paddle_tpu.profiler.mfu import (
        MFUMeter, transformer_train_flops,
    )
    import jax

    import os

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # round-5 recipe: B16 + selective remat + fused lm-head+CE (the
        # (B*S, 50304) logits buffers were ~5 GB) = 45.7% MFU, past the
        # 45% bar config #2 sat under since round 3. Sweep: B16/no-remat
        # and B32/selective OOM even fused; B24/selective 43.6%. Env
        # GPT2_* overrides kept for re-sweeps.
        batch = int(os.environ.get("GPT2_BATCH", "16"))
        remat = os.environ.get("GPT2_REMAT", "selective")
        fused = bool(int(os.environ.get("GPT2_FUSED", "1")))
        cfg = GPTConfig(
            vocab_size=50304, hidden_size=1024, num_hidden_layers=24,
            num_attention_heads=16, intermediate_size=4096,
            max_position_embeddings=1024, use_recompute=remat != "none",
            recompute_granularity=remat if remat != "none" else "full",
            fuse_linear_cross_entropy=fused, lce_chunk_rows=2048,
        )
        seq = 1024
    else:
        cfg = GPTConfig.tiny()
        batch, seq = 2, 32
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.astype("bfloat16")

    if cfg.fuse_linear_cross_entropy:
        from paddle_tpu.incubate.nn.functional import (
            fused_linear_cross_entropy,
        )

        def crit(out, labels):
            return fused_linear_cross_entropy(
                out.reshape([-1, cfg.hidden_size]),
                model.lm_head.weight, labels.reshape([-1]),
                chunk_rows=cfg.lce_chunk_rows)
    else:
        ce = paddle.nn.CrossEntropyLoss()

        def crit(out, labels):
            return ce(out.astype("float32").reshape([-1, cfg.vocab_size]),
                      labels.reshape([-1]))

    opt = paddle.optimizer.AdamW(
        1e-4, parameters=model.parameters(), multi_precision=True,
        moment_dtype="bfloat16",
    )
    step = JittedTrainStep(model, crit, opt)
    n = sum(int(np.prod(p._value.shape))
            for _, p in model.named_parameters())
    K = 10 if on_tpu else 2  # chained steps cancel dispatch overhead
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (K, batch, seq)))
    flops = transformer_train_flops(
        n, K * batch * seq, num_layers=cfg.num_hidden_layers, seq_len=seq,
        hidden=cfg.hidden_size, causal=True)
    meter = MFUMeter(flops, K * batch * seq)
    # min-of-3 REPEATS (round-5 verdict weak #4): the 45.7-vs-45 bar
    # crossing needs a run-to-run noise band, so the row reports the
    # best repeat plus the band across all three
    reps = [meter.measure(lambda: step.run_steps(ids, ids), warmup=1,
                          iters=3 if on_tpu else 2) for _ in range(3)]
    res = max(reps, key=lambda r: r["tokens_per_sec"])
    res["step_time_s"] /= K
    out = {"metric": "gpt2_345m_jit_tokens_per_sec",
           "value": round(res["tokens_per_sec"], 1), "unit": "tok/s",
           "params_m": round(n / 1e6),
           "tokens_per_sec_band": [
               round(min(r["tokens_per_sec"] for r in reps), 1),
               round(max(r["tokens_per_sec"] for r in reps), 1)]}
    if res.get("mfu"):
        out["mfu_pct"] = round(res["mfu"] * 100, 2)
        out["mfu_band_pct"] = [
            round(min(r["mfu"] for r in reps) * 100, 2),
            round(max(r["mfu"] for r in reps) * 100, 2)]
    return out


def ernie_engine():
    """Config #4: ERNIE pretrain step via the auto-parallel Engine."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import (
        ErnieConfig, ErnieForPretraining, BertPretrainingCriterion,
    )
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.io import Dataset
    import jax

    on_tpu = jax.default_backend() == "tpu"
    cfg = (ErnieConfig(num_hidden_layers=6, hidden_size=512,
                       num_attention_heads=8, intermediate_size=2048,
                       max_position_embeddings=512,
                       hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0)
           if on_tpu else ErnieConfig.tiny())
    batch, seq = (16, 256) if on_tpu else (4, 16)

    class Data(Dataset):
        def __init__(self, n=batch * 8):
            rng = np.random.RandomState(0)
            self.ids = rng.randint(
                1, cfg.vocab_size, (n, seq)).astype("i8")
            self.labels = np.full((n, seq), -100, "i8")
            self.labels[:, ::7] = self.ids[:, ::7]

        def __len__(self):
            return len(self.ids)

        def __getitem__(self, i):
            return self.ids[i], self.labels[i]

    paddle.seed(0)
    model = ErnieForPretraining(cfg)
    crit = BertPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    eng = Engine(model, lambda out, lb: crit(out[0], out[1], lb), opt)
    t0 = time.perf_counter()
    eng.fit(Data(), batch_size=batch, epochs=1, verbose=0)
    dt_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.fit(Data(), batch_size=batch, epochs=1, verbose=0)  # warm epoch
    dt_warm = time.perf_counter() - t0
    steps = 8
    return {"metric": "ernie_engine_tokens_per_sec",
            "value": round(steps * batch * seq / dt_warm, 1),
            "unit": "tok/s",
            "cold_tokens_per_sec": round(steps * batch * seq / dt_cold, 1),
            "note": "warm epoch; cold incl. first-step compile"}


def sd_unet():
    """Config #5: SD-UNet fused-inference denoising latency."""
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import (
        SDUNetConfig, UNet2DConditionModel, ddim_sample,
    )
    import jax

    on_tpu = jax.default_backend() == "tpu"
    cfg = (SDUNetConfig(block_out_channels=(64, 128),
                        cross_attention_dim=256, sample_size=32)
           if on_tpu else SDUNetConfig.tiny())
    steps = 20 if on_tpu else 3
    paddle.seed(0)
    unet = UNet2DConditionModel(cfg)
    unet.eval()
    rng = np.random.RandomState(0)
    lat = paddle.to_tensor(rng.randn(
        1, cfg.in_channels, cfg.sample_size, cfg.sample_size).astype("f4"))
    ctx = paddle.to_tensor(
        rng.randn(1, 16, cfg.cross_attention_dim).astype("f4"))

    def run():
        out = ddim_sample(unet, lat, ctx, num_inference_steps=steps)
        np.asarray(out._value)  # block

    run()  # compile
    dt = _time_it(run, warmup=1, iters=3)
    return {"metric": "sd_unet_denoise_latency_ms",
            "value": round(dt * 1000, 1), "unit": f"ms/{steps}-step sample"}


def resnet50_jit():
    """Config #1 under the perf path: same ResNet-50 step, one XLA
    program (forward+loss+backward+momentum update fused)."""
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.jit.train import JittedTrainStep

    paddle.seed(0)
    model = resnet50()
    ce = paddle.nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(0.1, parameters=model.parameters())
    step = JittedTrainStep(model, lambda out, y: ce(out, y), opt)
    rng = np.random.RandomState(0)
    batch = 64
    x = paddle.to_tensor(rng.randn(batch, 3, 224, 224).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, 1000, batch).astype("i8"))

    def run():
        loss = step(x, y)
        np.asarray(loss._value)

    run()  # compile
    dt = _time_it(run, warmup=1, iters=5)
    return {"metric": "resnet50_jit_images_per_sec",
            "value": round(batch / dt, 1), "unit": "img/s"}


def llama_decode():
    """Decode throughput: greedy generation with the KV-cache path, the
    whole loop in one dispatch (prefill + lax.scan of token steps)."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import generate_on_device
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=24, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            tensor_parallel=False,
        )
        batch, prompt, new = 8, 128, 128
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False)
        batch, prompt, new = 2, 8, 8
    dt = _decode_time(cfg, batch, prompt, new, quantize=False)
    dt_i8 = _decode_time(cfg, batch, prompt, new, quantize=True)
    return {"metric": "llama_375m_decode_tokens_per_sec",
            "value": round(batch * new / dt, 1), "unit": "tok/s",
            "batch": batch, "new_tokens": new,
            "int8_tokens_per_sec": round(batch * new / dt_i8, 1),
            "int8_speedup": round(dt / dt_i8, 2)}


def _decode_time(cfg, batch, prompt, new, quantize):
    """Median time of one greedy generate() call; optionally on the
    weight-only int8 artifact (shared by the decode benches so the two
    configs cannot drift)."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaForCausalLM
    from paddle_tpu.nlp.generation import generate_on_device

    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        1, cfg.vocab_size, (batch, prompt)))
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.astype("bfloat16")
    model.eval()
    if quantize:  # weight-only int8 serving artifact (verdict #5)
        from paddle_tpu.quantization import PTQ, QuantConfig

        ptq = PTQ(QuantConfig())
        model = ptq.convert(ptq.quantize(model))

    def run():
        out = generate_on_device(model, ids, max_new_tokens=new)
        np.asarray(out._value)

    run()  # compile
    return _time_it(run, warmup=1, iters=3)


def _bench():
    """Import the repo-root bench.py (the headline driver) so suite rows
    share its build_step recipe instead of re-implementing it."""
    import os
    import sys as _sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in _sys.path:
        _sys.path.insert(0, root)
    import bench

    return bench


def llama_941m_decode_int8():
    """Weight-only int8 serving at the scale where it pays: 941M-class
    decode (h2048 L16, GQA 32/8). The int8 artifact halves weight HBM
    residency AND traffic; at 375M the win is overhead-buried (see
    llama_decode's int8 fields) — here it is not."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import generate_on_device
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=16, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=2048,
            tensor_parallel=False)
        batch, prompt, new = 4, 64, 64
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False)
        batch, prompt, new = 2, 8, 8
    dt = _decode_time(cfg, batch, prompt, new, quantize=False)
    dt_i8 = _decode_time(cfg, batch, prompt, new, quantize=True)
    return {"metric": "llama_941m_decode_int8_speedup",
            "value": round(dt / dt_i8, 2), "unit": "x",
            "bf16_tokens_per_sec": round(batch * new / dt, 1),
            "int8_tokens_per_sec": round(batch * new / dt_i8, 1),
            "batch": batch, "new_tokens": new}


def _mfu_row(metric, res, **extra):
    """MFU row with honest off-TPU reporting: when the peak is unknown
    (CPU smoke) the row switches to a throughput metric name instead of
    recording 0% under the real MFU metric (bench.py's convention)."""
    if res.get("mfu"):
        out = {"metric": metric, "value": round(res["mfu"] * 100, 2),
               "unit": "%MFU"}
    else:
        out = {"metric": metric.replace("_mfu", "_tokens_per_sec")
               + "_cpu_smoke",
               "value": round(res["tokens_per_sec"], 1), "unit": "tok/s"}
    out.update(extra)
    return out


def llama_941m_train():
    """The rounds-1..3 headline: 941M h2048 Llama train MFU (kept as a
    tracked row after the 7B-shape config took over bench.py; its 47.7%
    is shape-bound — d=64 attention — per the BENCH_NOTES decomposition)."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig
    from paddle_tpu.profiler.mfu import MFUMeter, transformer_train_flops
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=16, num_attention_heads=32,
            max_position_embeddings=4096, tensor_parallel=False,
            use_recompute=False,
        )
        batch, seq, K = 2, 2048, 10
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False)
        batch, seq, K = 2, 64, 2
    model, step, _ = _bench().build_step(
        cfg, batch, seq,
        moment_dtype="bfloat16" if on_tpu else "float32")
    n = _bench().count_params(model)
    ids = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (K, batch, seq)))
    flops = transformer_train_flops(
        n, K * batch * seq, num_layers=cfg.num_hidden_layers, seq_len=seq,
        hidden=cfg.hidden_size, causal=True)
    meter = MFUMeter(flops, K * batch * seq)
    res = meter.measure(lambda: step.run_steps(ids, ids), warmup=1,
                        iters=3 if on_tpu else 2)
    res["step_time_s"] /= K
    return _mfu_row(
        "llama_941m_1chip_train_mfu", res, params_m=round(n / 1e6),
        tokens_per_sec_per_chip=round(res["tokens_per_sec_per_chip"]))


def llama_941m_packed_train():
    """Packed-varlen PRETRAINING (round-4 verdict #7): the 941M headline
    config trained end-to-end on ragged sequences packed to 4096 tokens
    per step, attention through `flash_attn_unpadded` (Pallas varlen
    kernel: dead cross-segment tiles skip compute and KV DMA), rope
    restarting per segment, boundary-masked criterion. MFU accounts
    attention FLOPs per segment (sum len_i^2), not the dense S^2."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.nlp import (
        LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    )
    from paddle_tpu.jit.train import JittedTrainStep
    from paddle_tpu.profiler.mfu import MFUMeter, transformer_train_flops
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=16, num_attention_heads=32,
            max_position_embeddings=4096, tensor_parallel=False,
            use_recompute=False,
        )
        lens = [1600, 800, 600, 400, 300, 200, 120, 76]  # sum 4096
        K = 10
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False)
        lens = [24, 16, 14, 10]  # sum 64
        K = 2
    T = sum(lens)
    cu_np = np.cumsum([0] + lens).astype(np.int32)

    paddle.seed(0)
    inner = LlamaForCausalLM(cfg)
    inner.astype("bfloat16")

    class _Packed(nn.Layer):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, ids, cu):
            return self.m(ids, cu_seqlens=cu)

    model = _Packed(inner)
    crit = LlamaPretrainingCriterion()

    def criterion(out, labels, cu):
        return crit(out.astype("float32"), labels, cu_seqlens=cu)

    opt = paddle.optimizer.AdamW(
        1e-4, parameters=model.parameters(), weight_decay=0.01,
        multi_precision=True,
        moment_dtype="bfloat16" if on_tpu else "float32",
    )
    step = JittedTrainStep(model, criterion, opt)
    n = _bench().count_params(model)
    ids = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (K, 1, T)))
    cu = paddle.to_tensor(np.broadcast_to(cu_np, (K, len(cu_np))).copy())
    # attention FLOPs scale with sum(len_i^2): fold into an effective
    # seq_len so the 6NT + attention accounting stays honest
    eff_seq = float(sum(l * l for l in lens)) / T
    flops = transformer_train_flops(
        n, K * T, num_layers=cfg.num_hidden_layers, seq_len=eff_seq,
        hidden=cfg.hidden_size, causal=True)
    meter = MFUMeter(flops, K * T)
    res = meter.measure(
        lambda: step.run_steps([ids, cu], [ids, cu]), warmup=1,
        iters=3 if on_tpu else 2)
    res["step_time_s"] /= K
    log(json.dumps(res, indent=2))
    return _mfu_row(
        "llama_941m_packed_varlen_train_mfu", res, segments=len(lens),
        tokens_per_step=T, eff_seq=round(eff_seq),
        tokens_per_sec_per_chip=round(res["tokens_per_sec_per_chip"]))


def llama_7b_shape_longctx():
    """Long-context training at 7B shape on ONE chip (SURVEY §5
    long-context row, measured): L=4 x h4096/d128, S=16384 with
    attention-only remat (S=32768 exceeds 16G even full-remat; the
    multi-chip escape hatch is ring/Ulysses CP over the sep axis,
    parallel==serial-tested on the virtual mesh)."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig
    from paddle_tpu.profiler.mfu import MFUMeter, transformer_train_flops
    import jax

    on_tpu = jax.default_backend() == "tpu"
    seq = 16384 if on_tpu else 128
    cfg = LlamaConfig(
        vocab_size=32000 if on_tpu else 128,
        hidden_size=4096 if on_tpu else 64,
        intermediate_size=11008 if on_tpu else 128,
        num_hidden_layers=4 if on_tpu else 2,
        num_attention_heads=32 if on_tpu else 4,
        max_position_embeddings=seq, tensor_parallel=False,
        use_recompute=True, recompute_granularity="core_attn",
        # round-5 recipe: fused lm-head+CE — at S16k the logits buffers
        # are ~4 GB and the fused op's extra-matmul share is negligible
        fuse_linear_cross_entropy=True, lce_chunk_rows=4096,
    )
    model, step, _ = _bench().build_step(
        cfg, 1, seq, moment_dtype="bfloat16" if on_tpu else "float32")
    n = _bench().count_params(model)
    K = 5 if on_tpu else 2
    ids = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (K, 1, seq)))
    flops = transformer_train_flops(
        n, K * seq, num_layers=cfg.num_hidden_layers, seq_len=seq,
        hidden=cfg.hidden_size, causal=True)
    meter = MFUMeter(flops, K * seq)
    res = meter.measure(lambda: step.run_steps(ids, ids), warmup=1,
                        iters=3 if on_tpu else 2)
    res["step_time_s"] /= K
    return _mfu_row(
        "llama_7b_shape_16k_longctx_train_mfu", res, seq=seq,
        params_m=round(n / 1e6),
        tokens_per_sec_per_chip=round(res["tokens_per_sec_per_chip"]))


def moe_dispatch():
    """MoE dispatch tiers head-to-head (round-4 verdict #4): grouped
    sort+`lax.ragged_dot` vs dense GShard (T,E,C) einsum, fwd+bwd+SGD
    at T=16384 tokens, E=8 experts, top-2, d_model 1024 / d_hidden 2816
    (Mixtral-ish slice). Parity is pytest-asserted
    (test_moe_grouped_matches_einsum_dispatch); this row measures the
    speedup."""
    import paddle_tpu as paddle
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    from paddle_tpu.jit.train import JittedTrainStep
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        t_tokens, d_model, d_hidden, experts, K = 16384, 1024, 2816, 8, 10
    else:
        t_tokens, d_model, d_hidden, experts, K = 256, 32, 64, 4, 2

    from paddle_tpu.profiler.mfu import MFUMeter

    def run(mode):
        paddle.seed(0)
        moe = MoELayer(d_model, d_hidden, num_experts=experts,
                       gate="gshard", capacity_factor=1.0,
                       activation="swiglu", dispatch_mode=mode)
        if on_tpu:
            moe.astype("bfloat16")

        def criterion(out, labels):
            return ((out.astype("float32") ** 2).mean()
                    + 0.01 * moe.l_aux)

        opt = paddle.optimizer.SGD(1e-3, parameters=moe.parameters())
        step = JittedTrainStep(moe, criterion, opt)
        x = paddle.to_tensor(np.random.RandomState(1).randn(
            K, t_tokens, d_model).astype(np.float32))
        if on_tpu:
            x = x.astype("bfloat16")
        meter = MFUMeter(0, t_tokens * K)  # timing only, no MFU claim
        res = meter.measure(lambda: step.run_steps([x], [x]),
                            warmup=1, iters=3)
        return res["step_time_s"] / K

    dt_grouped = run("grouped")
    dt_einsum = run("einsum")
    return {"metric": "moe_grouped_dispatch_speedup",
            "value": round(dt_einsum / dt_grouped, 2), "unit": "x",
            "tokens": t_tokens, "experts": experts,
            "grouped_ms_per_step": round(dt_grouped * 1e3, 2),
            "einsum_ms_per_step": round(dt_einsum * 1e3, 2),
            "grouped_tokens_per_sec": round(t_tokens / dt_grouped)}


def llama_7b_shape_train():
    """END-TO-END training MFU at Llama-2-7B dimensions (BASELINE config
    #3 / SURVEY §6 north star): h4096/d128/inter11008/vocab32000 — the
    full model path (embedding, L decoder layers, RMSNorm, lm head,
    cross-entropy, AdamW with f32 master + bf16 moments), not the
    round-3 single-layer microbench. L=4 layers fit one v5e-16G at this
    width (~1.07B params x 10B/param); per-layer dims are exactly 7B's,
    so layer MFU transfers and embedding/lm-head/optimizer overhead is
    MEASURED. Fallbacks on OOM: attention-only remat, then S=2048."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig
    from paddle_tpu.profiler.mfu import MFUMeter, transformer_train_flops
    import jax

    on_tpu = jax.default_backend() == "tpu"
    L = 4 if on_tpu else 2
    variants = ([(4096, False, None), (4096, True, "core_attn"),
                 (2048, False, None)] if on_tpu else [(64, False, None)])
    last_err = None
    for seq, remat, gran in variants:
        try:
            cfg = LlamaConfig(
                vocab_size=32000 if on_tpu else 128,
                hidden_size=4096 if on_tpu else 64,
                intermediate_size=11008 if on_tpu else 128,
                num_hidden_layers=L,
                num_attention_heads=32 if on_tpu else 4,
                max_position_embeddings=seq, tensor_parallel=False,
                use_recompute=remat, recompute_granularity=gran or "full",
            )
            batch = 1 if on_tpu else 2
            # same recipe as the bench.py headline, by construction
            model, step, _ = _bench().build_step(
                cfg, batch, seq,
                moment_dtype="bfloat16" if on_tpu else "float32")
            n = _bench().count_params(model)
            K = 10 if on_tpu else 2
            ids = paddle.to_tensor(np.random.RandomState(1).randint(
                0, cfg.vocab_size, (K, batch, seq)))
            flops = transformer_train_flops(
                n, K * batch * seq, num_layers=L, seq_len=seq,
                hidden=cfg.hidden_size, causal=True)
            log(f"7b-shape: L={L} seq={seq} remat={remat} "
                f"params={n/1e6:.0f}M")
            meter = MFUMeter(flops, K * batch * seq)
            res = meter.measure(
                lambda: step.run_steps(ids, ids), warmup=1,
                iters=3 if on_tpu else 2)
            res["step_time_s"] /= K
            log(json.dumps(res, indent=2))
            return _mfu_row(
                "llama_7b_shape_e2e_train_mfu", res,
                params_m=round(n / 1e6), layers=L, seq=seq, remat=remat,
                tokens_per_sec_per_chip=round(
                    res["tokens_per_sec_per_chip"]))
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            last_err = e
            # free the failed attempt's ~10GB of params/master/moments
            # before the next variant builds its own
            model = step = ids = meter = None
            log(f"7b-shape OOM at seq={seq} remat={remat}; trying next")
    raise last_err


def llama_7b_shape_b2_train():
    """Batch-2 production recipe at 7B shape (round-5 verdict #2, the
    B2 HBM cliff): fused lm-head+cross-entropy (chunked, no full-logits
    buffers — incubate.nn.functional.fused_linear_cross_entropy) lifts
    B2 from 61.6% to ~66.7% MFU. The measured decomposition (BENCH_NOTES
    round-5 table) shows compute scales linearly with batch; the
    remaining gap to B1 is whole-program heap-pressure scheduling."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig
    from paddle_tpu.profiler.mfu import MFUMeter, transformer_train_flops
    import jax

    on_tpu = jax.default_backend() == "tpu"
    seq = 4096 if on_tpu else 64
    cfg = LlamaConfig(
        vocab_size=32000 if on_tpu else 128,
        hidden_size=4096 if on_tpu else 64,
        intermediate_size=11008 if on_tpu else 128,
        num_hidden_layers=4 if on_tpu else 2,
        num_attention_heads=32 if on_tpu else 4,
        max_position_embeddings=seq, tensor_parallel=False,
        fuse_linear_cross_entropy=True,
    )
    cfg.lce_chunk_rows = 2048 if on_tpu else 64
    batch = 2
    model, step, _ = _bench().build_step(
        cfg, batch, seq, moment_dtype="bfloat16" if on_tpu else "float32")
    n = _bench().count_params(model)
    K = 10 if on_tpu else 2
    ids = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (K, batch, seq)))
    flops = transformer_train_flops(
        n, K * batch * seq, num_layers=cfg.num_hidden_layers, seq_len=seq,
        hidden=cfg.hidden_size, causal=True)
    meter = MFUMeter(flops, K * batch * seq)
    res = meter.measure(lambda: step.run_steps(ids, ids), warmup=1,
                        iters=3 if on_tpu else 2)
    res["step_time_s"] /= K
    return _mfu_row(
        "llama_7b_shape_b2_fused_lce_train_mfu", res,
        params_m=round(n / 1e6), seq=seq, batch=batch,
        tokens_per_sec_per_chip=round(res["tokens_per_sec_per_chip"]))


def llama_7b_shape_serving():
    """Serving at the HEADLINE shape (round-5 verdict #4): the L=4
    h4096/d128 GQA-32/8 stack through FusedMultiTransformer decode
    (bf16 and weight-only int8) plus the paged-attention decode step
    with bf16 vs int8 KV pools (round-5 in-kernel dequant). Decode
    steps are chained data-dependently inside one jit (axon timing
    methodology) — ms/token is the marginal chained-step cost."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.incubate.nn.fused_transformer import _fused_stack

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        E, H, HK, FFN, L = 4096, 32, 8, 11008, 4
        B, prompt, new_probe = 4, 128, 16
        dt = "bfloat16"
    else:
        E, H, HK, FFN, L = 64, 4, 2, 128, 2
        B, prompt, new_probe = 2, 8, 2
        dt = "float32"
    D = E // H
    smax = prompt + 140

    paddle.seed(0)
    fmt = FusedMultiTransformer(
        E, H, FFN, activation="swiglu", norm_type="rmsnorm",
        num_layers=L, num_key_value_heads=HK,
        use_neox_rotary_style=False)
    fmt.astype(dt)
    rng = np.random.RandomState(0)

    def fmt_decode_ms():
        kc, vc = fmt.gen_cache(B, smax, dtype=dt)
        src = paddle.to_tensor(
            rng.randn(B, prompt, E).astype("f4") * 0.02).astype(dt)
        _, (kc2, vc2) = fmt(src, caches=(kc, vc), time_step=0)
        weights = [
            fmt.ln_scale, fmt.ln_bias, fmt.qkv_weight, fmt.qkv_bias,
            fmt.linear_weight, fmt.linear_bias, fmt.ffn_ln_scale,
            fmt.ffn_ln_bias, fmt.ffn1_weight, fmt.ffn1_bias,
            fmt.ffn2_weight, fmt.ffn2_bias, fmt.qkv_weight_scale,
            fmt.linear_weight_scale, fmt.ffn1_weight_scale,
            fmt.ffn2_weight_scale,
        ]
        w_idx = [i for i, w in enumerate(weights) if w is not None]
        w_vals = [weights[i]._value for i in w_idx]

        def chain(wv, src_v, kc_v, vc_v, n):
            # n TRACED (one compile; distinct n → distinct dispatches,
            # dodging both recompiles and the axon dispatch cache)
            wt = {i: v for i, v in zip(w_idx, wv)}

            def body(j, carry):
                s_v, k_v, v_v = carry
                return _fused_stack(s_v, k_v, v_v, None, wt, fmt,
                                    prompt + j, decode=True)

            return jax.lax.fori_loop(
                0, n, body, (src_v, kc_v, vc_v))[0]

        jc = jax.jit(chain)
        tok = paddle.to_tensor(
            rng.randn(B, 1, E).astype("f4") * 0.02).astype(dt)._value
        args = (w_vals, tok, kc2._value, vc2._value)
        float(jnp.sum(jc(*args, 2).astype(jnp.float32)))  # compile+warm
        pers = []
        for r in range(3):
            n = new_probe + r
            ts = {}
            for m in (n, 2 * n):
                t0 = time.perf_counter()
                out = jc(*args, m)
                float(jnp.sum(out.astype(jnp.float32)))
                ts[m] = time.perf_counter() - t0
            pers.append((ts[2 * n] - ts[n]) / n)
        return float(np.median(pers)) * 1000  # median rides out tunnel noise

    ms_bf16 = fmt_decode_ms()
    fmt.quantize_weight_only()
    ms_int8 = fmt_decode_ms()

    # paged decode step, bf16 vs int8 KV pools (ragged serving contexts)
    from paddle_tpu.ops.pallas.paged_attention import paged_decode_attention

    bs = 256 if on_tpu else 32
    nb = 136 if on_tpu else 16
    pb = 8 if on_tpu else 2
    lens = (rng.randint(100, 4000, pb) if on_tpu
            else rng.randint(4, 20, pb)).astype(np.int32)
    steps = int(np.ceil((lens.max() + 1) / bs))
    tables = np.full((pb, steps), 0, np.int32)
    nxt = 0
    for i, ln in enumerate(lens):
        for bi in range(int(np.ceil(ln / bs))):
            tables[i, bi] = nxt % nb
            nxt += 1
    kp = (rng.randn(nb, bs, HK, D) * 0.3).astype("f4")
    vp = (rng.randn(nb, bs, HK, D) * 0.3).astype("f4")
    ks = (np.abs(kp).max(axis=(0, 1, 3)) / 127.0).astype("f4")
    vs = (np.abs(vp).max(axis=(0, 1, 3)) / 127.0).astype("f4")
    kp8 = np.clip(np.round(kp / ks[None, None, :, None]),
                  -128, 127).astype(np.int8)
    vp8 = np.clip(np.round(vp / vs[None, None, :, None]),
                  -128, 127).astype(np.int8)
    cdt = jnp.bfloat16 if on_tpu else jnp.float32

    def paged_us(int8):
        kpj = jnp.asarray(kp8 if int8 else kp.astype(cdt))
        vpj = jnp.asarray(vp8 if int8 else vp.astype(cdt))
        tb = jnp.asarray(tables)
        ln = jnp.asarray(lens)
        q0 = jnp.asarray((rng.randn(pb, H, D) * 0.3).astype("f4")).astype(cdt)

        def chain(q, n):
            def body(i, qq):
                o = paged_decode_attention(
                    qq, kpj, vpj, tb, ln,
                    k_scale=jnp.asarray(ks) if int8 else None,
                    v_scale=jnp.asarray(vs) if int8 else None)
                return (qq + o * jnp.bfloat16(1e-3)).astype(qq.dtype) \
                    if on_tpu else qq + o * 1e-3
            return jax.lax.fori_loop(0, n, body, q)

        jc = jax.jit(chain)  # n traced: one compile
        float(jnp.sum(jc(q0, 2).astype(jnp.float32)))
        pers = []
        for r in range(3):
            # long chains: the per-step cost is ~1 ms and tunnel noise is
            # of the same order, so the N-vs-2N window must be >> noise
            n = (64 if on_tpu else 8) + r
            ts = {}
            for m in (n, 2 * n):
                t0 = time.perf_counter()
                float(jnp.sum(jc(q0, m).astype(jnp.float32)))
                ts[m] = time.perf_counter() - t0
            pers.append((ts[2 * n] - ts[n]) / n)
        return float(np.median(pers)) * 1e6

    us_pool = paged_us(False)
    us_pool8 = paged_us(True)
    live_blocks = int(sum(int(np.ceil(ln / bs)) for ln in lens))
    blk_bytes = bs * HK * D
    kv_bytes_bf16 = live_blocks * blk_bytes * 2 * 2  # k+v, 2B
    kv_bytes_int8 = live_blocks * blk_bytes * 2      # k+v, 1B
    cache_bytes_fmt = L * B * smax * HK * D * 2 * 2

    return {
        "metric": "llama_7b_shape_serving_decode",
        "value": round(B / (ms_bf16 / 1000)), "unit": "tok/s",
        "ms_per_token_bf16": round(ms_bf16, 2),
        "ms_per_token_int8": round(ms_int8, 2),
        "int8_speedup": round(ms_bf16 / ms_int8, 2),
        "batch": B, "fmt_cache_bytes": cache_bytes_fmt,
        "paged_step_us_bf16": round(us_pool),
        "paged_step_us_int8kv": round(us_pool8),
        "paged_kv_bytes_bf16": kv_bytes_bf16,
        "paged_kv_bytes_int8": kv_bytes_int8,
    }


def graph_audit():
    """Compiled-graph budget gate for the bench recipes: before trusting
    any perf number, assert the registered analysis budgets still hold
    (0 involuntary remats, bounded collective counts/bytes, bf16 graphs
    stay bf16, train state donated). One JSON row aggregating the
    per-recipe census; a budget violation reports as the standard
    error row, failing the suite entry loudly."""
    from paddle_tpu import analysis

    rows = {}
    for name in sorted(analysis.RECIPES):
        report = analysis.run_recipe(name)  # raises BudgetViolation
        rows[name] = {
            "collectives": {
                k: report.collectives[k].count
                for k in analysis.COLLECTIVE_KINDS
                if report.collectives[k].count
            },
            "collective_bytes": report.total_collective_bytes,
            "remat": len(report.remat_events),
            "f32_matmuls": (len(report.dtype.f32_compute)
                            if report.dtype else None),
        }
    return {"metric": "graph_audit_budgets_ok", "value": len(rows),
            "unit": "recipes", **{f"recipe_{k}": v
                                  for k, v in rows.items()}}


def graph_fingerprint():
    """Golden drift gate for the audited recipes: compare each live
    fingerprint (collectives, remat, donation, dtype, host syncs,
    memory, sharding) against tests/goldens/<recipe>.json. Drift
    raises — a perf number measured on a silently-drifted graph is not
    comparable to the history, so the suite fails loudly first."""
    from paddle_tpu import analysis

    drifted = {}
    checked = 0
    for name in sorted(analysis.RECIPES):
        recipe = analysis.build_recipe(name)
        try:
            report = recipe.audit()
        finally:
            recipe.close()
        try:
            analysis.check_recipe_fingerprint(name, report)
            checked += 1
        except analysis.FingerprintMismatch as e:
            drifted[name] = e.diff
    if drifted:
        raise analysis.FingerprintMismatch(
            "+".join(sorted(drifted)),
            [ln for diff in drifted.values() for ln in diff])
    return {"metric": "graph_fingerprint_goldens_ok", "value": checked,
            "unit": "recipes"}


def cost_model():
    """Static cost model vs reality (ISSUE 16): roofline floors vs
    measured single-chip dispatch walls plus the guarded cross-source
    flops-agreement ratio (see scripts/bench_cost.py and
    BENCH_COST_r17.json)."""
    import os
    import sys as _sys

    here = os.path.dirname(os.path.abspath(__file__))
    if here not in _sys.path:
        _sys.path.insert(0, here)
    import bench_cost

    return bench_cost.cost_model()


def _bench_serving():
    """Import scripts/bench_serving.py wherever the suite is run from
    (same trick as _bench for the repo-root driver)."""
    import os
    import sys as _sys

    here = os.path.dirname(os.path.abspath(__file__))
    if here not in _sys.path:
        _sys.path.insert(0, here)
    import bench_serving

    return bench_serving


def serving_engine():
    """Continuous-batching engine under ragged Poisson arrivals (ISSUE 2
    tentpole; full methodology + artifact in scripts/bench_serving.py
    and BENCH_SERVING_*.json)."""
    return _bench_serving().serving_engine()


def speculative_decode():
    """Speculative greedy decode vs the one-dispatch loop (round-5
    VERDICT weak #1; see scripts/bench_serving.py)."""
    return _bench_serving().speculative_decode()


def speculative_serving():
    """On-device speculative serving round vs the plain decode quantum
    (ISSUE 3 tentpole; methodology + stand-in pair construction in
    scripts/bench_serving.py, artifact BENCH_SPEC_r07.json)."""
    return _bench_serving().speculative_serving()


def serving_obs_overhead():
    """Runtime-observability cost gate (ISSUE 5): decode-quantum
    throughput with full instrumentation (metrics registry + request
    tracing) vs rich-hooks-off — must stay <3% on the CPU smoke
    config; the compiled quantum is fingerprint-identical either way
    (see scripts/bench_serving.py)."""
    return _bench_serving().serving_obs_overhead()


def fault_recovery_overhead():
    """Resilience-tier price when nothing goes wrong (ISSUE 13):
    guarded dispatch + quantum watchdog + per-step pool audit live
    with the fault injector DISARMED vs the plain obs="off" engine —
    same <3% bar and fingerprint-identical quantum as
    serving_obs_overhead (see scripts/bench_serving.py, artifact
    BENCH_RESILIENCE_r14.json)."""
    return _bench_serving().fault_recovery_overhead()


def attribution_overhead():
    """Cost-ledger cost gate (ISSUE 10): decode-quantum throughput
    with the per-token attribution ledger live vs the same fully-
    instrumented engine with a no-op ledger stand-in — prices exactly
    the attribution bookkeeping, same <3% bar and fingerprint-
    identical quantum as serving_obs_overhead (see
    scripts/bench_serving.py, artifact BENCH_ATTR_r12.json)."""
    return _bench_serving().attribution_overhead()


def slo_overhead():
    """Operability-tier cost gate (ISSUE 6): decode-quantum throughput
    with per-dispatch SLO burn-rate evaluation + flight-recorder
    journaling (anomaly capture forced) vs obs="off" — same <3% bar
    and fingerprint-identical quantum as serving_obs_overhead (see
    scripts/bench_serving.py, artifact BENCH_SLO_r09.json)."""
    return _bench_serving().slo_overhead()


def serving_overload():
    """Front-door acceptance row (ISSUE 7): p95 TTFT + shed rate under
    a >capacity Poisson burst through paddle.inference.serve(), shed
    arm (SLO-burn-rate admission + backpressure + priority preemption)
    vs the no-shed pass-through — shedding must bound the admitted
    TTFT tail while the no-shed arm degrades with the backlog (see
    scripts/bench_serving.py, artifact BENCH_FRONTDOOR_r10.json)."""
    return _bench_serving().serving_overload()


def shared_prefix():
    """Prefix-cache acceptance row (ISSUE 9): ragged Poisson arrivals
    over one common system prompt, prefix_cache=True vs the unshared
    engine on the same arrival trace — prefill tokens and novel pool
    residency must scale with unique tokens, streams bit-identical
    (see scripts/bench_serving.py, artifact BENCH_PREFIX_r11.json)."""
    return _bench_serving().shared_prefix()


def serving_tp():
    """TP-sharded serving acceptance row (ISSUE 11): the same weights
    and request set through tp=1 vs tp=2 engines — streams must be
    bit-identical, per-chip KV pool residency halves (the guarded
    2.0x ratio), quantum step time + collective census ride along
    (see scripts/bench_serving.py, artifact BENCH_TP_r13.json)."""
    return _bench_serving().serving_tp()


def serving_int8():
    """Quantized-serving acceptance row (ISSUE 14): the same ragged
    request set through dequantized-float / weight-only-int8 / fully
    quantized (int8 weights + int8 KV) engines — the weight-only arm
    must equal the dequant oracle bit-for-bit, and the guarded
    (4d)/(d+4) pool-residency ratio proves the int8 pool is real
    (see scripts/bench_serving.py, artifact BENCH_INT8_r15.json)."""
    return _bench_serving().serving_int8()


def serving_cluster():
    """Cluster-tier acceptance row (ISSUE 15): prefix-affinity routing
    vs round-robin on a multi-tenant shared-system-prompt trace
    (router hit-rate advantage + cached-token ratio) and
    admitted-throughput scaling replicas 1->4 under per-door
    backpressure with cluster shed coordination; cluster-of-4 streams
    asserted bit-identical to cluster-of-1 in-run (see
    scripts/bench_serving.py, artifact BENCH_CLUSTER_r16.json)."""
    return _bench_serving().serving_cluster()


def dispatch_decomposition():
    """Multi-quantum host-gap acceptance row (ISSUE 17): steady-state
    decode dispatch wall time decomposed into host-side scheduling vs
    the device program across K in {1, 4, 16} on-device quanta per
    dispatch, plus the fused paged-attention path vs the XLA-gather
    oracle — host us/token at K=16 over K=1 must be < 1 and
    every arm's greedy streams are asserted bit-identical in-run (see
    scripts/bench_serving.py, artifact BENCH_HOSTGAP_r18.json)."""
    return _bench_serving().dispatch_decomposition()


CONFIGS = {
    "graph_audit": graph_audit,
    "graph_fingerprint": graph_fingerprint,
    "cost_model": cost_model,
    "serving_engine": serving_engine,
    "speculative_decode": speculative_decode,
    "speculative_serving": speculative_serving,
    "serving_obs_overhead": serving_obs_overhead,
    "fault_recovery_overhead": fault_recovery_overhead,
    "attribution_overhead": attribution_overhead,
    "slo_overhead": slo_overhead,
    "serving_overload": serving_overload,
    "shared_prefix": shared_prefix,
    "serving_tp": serving_tp,
    "serving_int8": serving_int8,
    "serving_cluster": serving_cluster,
    "dispatch_decomposition": dispatch_decomposition,
    "resnet50_eager": resnet50_eager,
    "resnet50_jit": resnet50_jit,
    "gpt2_jit": gpt2_jit,
    "ernie_engine": ernie_engine,
    "sd_unet": sd_unet,
    "llama_decode": llama_decode,
    "llama_941m_decode_int8": llama_941m_decode_int8,
    "llama_941m_train": llama_941m_train,
    "llama_941m_packed_train": llama_941m_packed_train,
    "llama_7b_shape_train": llama_7b_shape_train,
    "llama_7b_shape_b2_train": llama_7b_shape_b2_train,
    "llama_7b_shape_serving": llama_7b_shape_serving,
    "llama_7b_shape_longctx": llama_7b_shape_longctx,
    "moe_dispatch": moe_dispatch,
}


def main():
    names = sys.argv[1:] or list(CONFIGS)
    for name in names:
        log(f"== {name} ==")
        t0 = time.perf_counter()
        try:
            out = CONFIGS[name]()
            out["wall_s"] = round(time.perf_counter() - t0, 1)
            print(json.dumps(out), flush=True)
        except Exception as e:
            print(json.dumps(
                {"metric": name, "error": f"{type(e).__name__}: {e}"[:200]}),
                flush=True)


if __name__ == "__main__":
    main()
