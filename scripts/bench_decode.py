"""Secondary benchmark: decode tokens/sec through the on-device greedy
loop (KV cache + Pallas decode kernel + lm head, whole loop one dispatch).

Not the driver headline (bench.py prints that); run manually:
    python scripts/bench_decode.py
"""
import json
import sys
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import generate_on_device

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=24, num_attention_heads=16,
            max_position_embeddings=4096, tensor_parallel=False)
        batch, s_in, new = 8, 128, 128
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False)
        batch, s_in, new = 2, 8, 8

    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    if on_tpu:
        m.astype("bfloat16")
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, s_in)))

    t0 = time.perf_counter()
    out = generate_on_device(m, ids, max_new_tokens=new)
    _ = out.numpy()
    compile_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = generate_on_device(m, ids, max_new_tokens=new)
    _ = out.numpy()
    run_t = time.perf_counter() - t0

    toks = batch * new
    print(f"compile {compile_t:.1f}s run {run_t:.3f}s", file=sys.stderr)
    print(json.dumps({
        "metric": "llama_375m_decode_tokens_per_sec",
        "value": round(toks / run_t, 1),
        "unit": "tokens/s",
        "batch": batch, "new_tokens": new,
    }))


if __name__ == "__main__":
    main()
