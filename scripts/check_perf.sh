#!/usr/bin/env bash
# Perf sentinel: validate + index every BENCH_*.json / MULTICHIP_*.json
# (schema drift fails), compare against the checked-in BENCH_INDEX.json
# (staleness fails), and enforce the declared PerfBudget bands (a
# guarded ratio outside its band fails with a field-level diff).
# Pure stdlib — runs in ~100ms, no jax import.
#
#     scripts/check_perf.sh
#
# After an INTENTIONAL bench re-run or band move:
#     python scripts/validate_bench.py --update   # then review+commit
# the BENCH_INDEX.json diff like a golden (README "performance
# sentinel" documents the honest-loosening protocol).
set -euo pipefail
cd "$(dirname "$0")/.."

python scripts/validate_bench.py --check
echo "check_perf: bench trajectory indexed + perf budgets green"
