"""Serving benches: continuous-batching throughput under ragged
Poisson arrivals, and speculative decode vs the one-dispatch loop.

    PYTHONPATH=. python scripts/bench_serving.py [serving_engine|speculative_decode ...]

``serving_engine`` drives :class:`paddle_tpu.serving.ServingEngine` —
many ragged requests (Poisson arrivals, log-ragged prompt/output
lengths) multiplexed over one paged KV pool and one jitted decode
quantum — and reports steady-state generated-token throughput against
the sequential batch-1 ``generate_on_device`` baseline measured in the
same process on the same model (the engine must win by keeping slots
full while requests come and go; the arrival rate is set to ~2x the
baseline's token rate so the queue stays non-empty and the measurement
is capacity, not offered load). Off TPU the row reports under a
``_cpu_smoke`` metric name (bench_suite convention) — the speedup
ratio is still meaningful (batching amortizes per-dispatch overhead)
but the tok/s is not a TPU claim.

``speculative_decode`` closes round-5 VERDICT weak #1: tok/s and
acceptance-rate-vs-speedup for ``speculative_greedy_search`` (self-
draft: the target's own first layers as draft would need a trained
head, so the draft here is a narrower random-init model — acceptance
is then near-floor and the row records the WORST case; with
acceptance=1 forced (draft=target) it records the best case. Both
arms vs the ``generate_on_device`` single-dispatch loop at the same
shape.)

``speculative_serving`` (ISSUE 3) is the on-device answer to that
row's structural conclusion: ``ServingEngine(spec_draft=...)`` makes
the whole draft-γ + verify round ONE dispatch, and this row measures
its steady-state decode capacity against the plain decode quantum on
the same target (interleaved windows, median ratio — the same
methodology as the capacity probe above). Random-init models cannot
exhibit trained-pair acceptance, so the headline arm uses a
DISTILLATION STAND-IN: the draft shares the target's embedding, first
layer(s), final norm and lm head, and the target's remaining layers
get their output projections scaled by a small ``eps`` — a
deep-but-low-gain tail that yields realistic (~0.95) acceptance while
the target honestly pays its full depth. The independent random-init
draft arm (near-floor acceptance) is recorded alongside as the floor,
plus the dispatch-count decomposition either way.

``serving_obs_overhead`` (ISSUE 5) prices the runtime observability
layer: steady-state decode-quantum throughput of an engine with FULL
instrumentation (metrics registry + per-request Chrome tracing,
``trace=True``) against one with the rich hooks disabled
(``obs="off"``), interleaved windows, median ratio — the acceptance
bar is <3% overhead on the CPU smoke config, and the jitted program is
IDENTICAL either way (same golden fingerprint; only host boundary work
differs). The ``serving_engine`` row also dumps the obs registry's
view of the run (ttft/e2e observation counts, windowed tok/s) so the
bench artifact carries the same numbers a scrape would.

``slo_overhead`` (ISSUE 6) prices the operability tier the same way:
an engine evaluating its SLO burn rates after every dispatch (the
shedding scheduler's poll pattern) with the per-request flight
recorder journaling — anomaly capture forced on every retirement — vs
``obs="off"``, same interleaved-window methodology, same <3% bar.
Artifact BENCH_SLO_r09.json.

``serving_overload`` (ISSUE 7) is the front door's acceptance row:
the same model behind ``paddle.inference.serve()`` under a >capacity
Poisson arrival burst (offered load ~3x the engine's calibrated token
capacity, priorities mixed INTERACTIVE/NORMAL/BATCH), once with the
stock shedding policy (SLO-burn-rate admission + queue backpressure +
priority preemption) and once with the pass-through ``no_shed_policy``
— the shed arm must BOUND admitted p95 TTFT while the no-shed arm
degrades linearly with the backlog, and the shed rate prices the
traffic it refused to do so. Both arms end with a graceful ``drain()``
(finish in-flight, flush the flight recorder). Artifact
BENCH_FRONTDOOR_r10.json.

``shared_prefix`` (ISSUE 9) is the prefix-cache acceptance row:
ragged Poisson arrivals where every prompt opens with one COMMON
SYSTEM PROMPT (full cache blocks) followed by a unique log-ragged
tail, run twice on the same arrival trace — ``prefix_cache=True`` vs
the unshared engine. The shared arm must (a) prefill ~O(unique
tokens): its prefill-token total drops by ~the aliased system-prompt
tokens, (b) hold ~O(unique tokens) of NOVEL pool residency: its
post-warmup peak-blocks high-water mark stays under the unshared
arm's, and (c) stream BIT-IDENTICAL tokens (greedy; copy-on-write
isolates every writer). TTFT p50/p95 ride along — on TPU the prefill
saving is the TTFT win; on the CPU smoke the eager ragged prefill
dispatches dominate so the token ratios are the claim and the metric
carries the ``_cpu_smoke`` suffix. Artifact BENCH_PREFIX_r11.json.

``fault_recovery_overhead`` (ISSUE 13) prices the resilience tier the
same way: an engine with the guarded dispatch + quantum watchdog +
per-step pool audit live but its deterministic fault injector DISARMED
(the production configuration — seams threaded, nothing firing) vs the
plain ``obs="off"`` engine, interleaved windows, median ratio, same
<3% bar. The compiled quantum is byte-identical either way (the
injector touches host boundaries only). Artifact
BENCH_RESILIENCE_r14.json.

``serving_int8`` (ISSUE 14) is the quantized-serving acceptance row:
the same ragged request set through a float engine holding the
DEQUANTIZED int8 matrices (the exact floats the int8 kernel's fused
dequant feeds its matmuls — so the weight-only-int8 arm must match it
bit-for-bit, an equality oracle), a weight-only-int8 arm, and a fully
quantized arm (int8 weights + int8 KV pool with per-row f32 scales).
The guarded metric is KV pool residency float/int8 at a deterministic
allocation point — exactly (4d)/(d+4) by construction, decaying to
1.0 if the pool silently falls back to float storage. Artifact
BENCH_INT8_r15.json.

``serving_cluster`` (ISSUE 15) is the cluster tier's acceptance row:
N in-process ``ServingEngine`` replicas behind the
``ClusterRouter``/``ClusterFrontDoor``. Arm (a): a multi-tenant
shared-system-prompt trace (tenant-interleaved arrivals) routed by
prefix affinity vs the round-robin control — the guarded claim is the
router's affinity HIT-RATE advantage, with the aggregate
cached-prompt-token ratio alongside; arm (b): admitted-throughput
scaling replicas 1->4 under per-door queue backpressure with cluster
shed coordination (a request sheds only when every replica refused).
Both guarded ratios are DETERMINISTIC — routing is a pure host
function of the trace, and admission depends only on queue depths at
the submission points — so their perf budgets carry no noise band;
cluster-of-4 streams are asserted bit-identical to cluster-of-1 (and
to the round-robin arm) inside the row. Artifact
BENCH_CLUSTER_r16.json.

``dispatch_decomposition`` (ISSUE 17) decomposes a steady-state decode
dispatch's wall time into host-side scheduling vs the device program,
across the multi-quantum driver's K in {1, 4, 16} (one dispatch
retires K quanta on-device under ``lax.while_loop``) and the fused
online-softmax paged-attention path vs the XLA-gather oracle. The
guarded metric is host-us-per-token(K=16)/host-us-per-token(K=1) —
strictly < 1, one dispatch's host boundary amortized over K*T tokens —
and every arm replays the same ragged greedy request set with
streams asserted bit-identical in-run. Artifact BENCH_HOSTGAP_r18.json.

All rows are registered in scripts/bench_suite.py (``serving_engine``,
``speculative_decode``, ``speculative_serving``,
``serving_obs_overhead``, ``fault_recovery_overhead``,
``slo_overhead``, ``serving_overload``, ``shared_prefix``,
``serving_tp``, ``serving_int8``, ``serving_cluster``,
``dispatch_decomposition``);
results & methodology in BENCH_NOTES.md, artifact BENCH_SPEC_r07.json.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _serving_cfg():
    """The 7B serving shape (llama_7b_shape_serving's stack: h4096/d128
    GQA-32/8, L=4 layers fit one 16G chip) on TPU; tiny off-TPU."""
    import jax
    from paddle_tpu.nlp import LlamaConfig

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_hidden_layers=4, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=2048,
            tensor_parallel=False)
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False)
    return cfg, on_tpu


def _build_model(cfg, on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.astype("bfloat16")
    model.eval()
    return model


def _request_set(cfg, on_tpu, rng):
    """Ragged prompts/outputs: log-uniform lengths (short-head heavy,
    like real traffic), fixed seed."""
    if on_tpu:
        n_req, p_lo, p_hi, n_lo, n_hi = 48, 32, 256, 32, 128
    else:
        n_req, p_lo, p_hi, n_lo, n_hi = 12, 4, 16, 6, 16
    p_lens = np.exp(rng.uniform(np.log(p_lo), np.log(p_hi),
                                n_req)).astype(int)
    n_news = np.exp(rng.uniform(np.log(n_lo), np.log(n_hi),
                                n_req)).astype(int)
    return [(rng.randint(1, cfg.vocab_size, int(p)).astype(np.int32),
             int(n)) for p, n in zip(p_lens, n_news)]


def _seq_batch1_tok_s(model, cfg, on_tpu):
    """The baseline the engine must beat: batch-1 sequential
    ``generate_on_device`` at a fixed representative shape (one compile,
    timed warm — the kindest possible sequential number)."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp.generation import generate_on_device

    prompt, new = (128, 128) if on_tpu else (8, 8)
    ids = paddle.to_tensor(np.random.RandomState(3).randint(
        1, cfg.vocab_size, (1, prompt)))

    def run():
        out = generate_on_device(model, ids, max_new_tokens=new)
        np.asarray(out._value)

    run()  # compile
    best = float("inf")
    for _ in range(5):  # min-of-5 rides out host-load noise
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return new / best


def serving_engine():
    """Continuous batching under ragged Poisson arrivals vs sequential
    batch-1 decode — the tok/s-under-load number (ISSUE 2 tentpole)."""
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    requests = _request_set(cfg, on_tpu, rng)

    seq_tok_s = _seq_batch1_tok_s(model, cfg, on_tpu)
    log(f"sequential batch-1 baseline: {seq_tok_s:.1f} tok/s")

    num_slots = 8 if on_tpu else 16
    block_size = 32 if on_tpu else 8
    decode_quantum = 16 if on_tpu else 8
    quanta = 6  # capacity-probe dispatches: 1 warm + 5 timed windows
    probe_ctx = 8 + decode_quantum * quanta + 8
    # size the pool's table width to the workload, not the model's
    # absolute max: the XLA-gather fallback (and the pool itself) pay
    # for table width, and a serving config always bounds context
    max_ctx = max(max(p.shape[0] + n for p, n in requests), probe_ctx)
    max_ctx = -(-max_ctx // block_size) * block_size
    engine = ServingEngine(
        model, num_slots=num_slots, block_size=block_size,
        prefill_chunk=128 if on_tpu else 8,
        decode_quantum=decode_quantum, max_context=max_ctx)

    # warmup: compile the quantum + the mixed-step shapes on a clone of
    # the request distribution, then reset every obs surface (registry
    # counters AND histograms/series — the old idiom hand-zeroed the
    # legacy stats view and left warmup observations in the histograms)
    for p, n in requests[: num_slots + 2]:
        engine.submit(p, max_new_tokens=n)
    engine.run()
    engine.completed.clear()
    engine.obs.reset()
    log("warmup done; timed ragged-arrival phase")

    # open-loop Poisson arrivals at ~2x the baseline token rate: the
    # queue stays non-empty, so throughput measures engine CAPACITY
    mean_new = float(np.mean([n for _, n in requests]))
    req_rate = 2.0 * seq_tok_s / mean_new  # requests/sec offered
    gaps = rng.exponential(1.0 / req_rate, len(requests))
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0  # first request at t=0 starts the clock

    submitted = 0
    t0 = time.perf_counter()
    while submitted < len(requests) or engine.has_work:
        now = time.perf_counter() - t0
        while (submitted < len(requests)
               and arrivals[submitted] <= now):
            p, n = requests[submitted]
            engine.submit(p, max_new_tokens=n)
            submitted += 1
        if engine.has_work:
            engine.step()
        elif submitted < len(requests):
            time.sleep(min(arrivals[submitted] - now, 0.01))
    wall = time.perf_counter() - t0

    stats = engine.engine_stats()
    gen = stats["generated_tokens"]
    tok_s = gen / wall
    done = engine.completed
    ttft = sorted((r.first_token_time - r.arrival_time) * 1e3
                  for r in done)
    lat = sorted((r.finish_time - r.arrival_time) * 1e3 for r in done)

    # steady-state decode CAPACITY: all slots occupied, no admissions
    # pending — the timed region is pure jitted-quantum dispatches (the
    # program the serving_decode_step Budget pins). This isolates the
    # decode hot loop from the eager chunked-prefill path, whose
    # ragged-shape op dispatches dominate small-model/CPU runs. The
    # capacity-vs-batch1 ratio is computed from INTERLEAVED timing
    # windows (sequential window, quantum window, 3 rounds, median
    # ratio) so host-load drift hits both sides of each ratio equally.
    import paddle_tpu as paddle
    from paddle_tpu.nlp.generation import generate_on_device

    q_tokens = engine.config.decode_quantum * quanta
    for i in range(num_slots):
        engine.submit(rng.randint(1, cfg.vocab_size, 8)
                      .astype(np.int32), max_new_tokens=q_tokens + 8)
    while engine.scheduler.prefilling() or not engine.scheduler.decoding():
        engine.step()
    engine._decode_quantum()  # warm

    s_prompt, s_new = (128, 128) if on_tpu else (8, 8)
    s_ids = paddle.to_tensor(np.random.RandomState(3).randint(
        1, cfg.vocab_size, (1, s_prompt)))

    def seq_window(calls):
        t0 = time.perf_counter()
        for _ in range(calls):
            np.asarray(generate_on_device(
                model, s_ids, max_new_tokens=s_new)._value)
        return calls * s_new / (time.perf_counter() - t0)

    def quantum_window(dispatches):
        g0 = int(engine._n_gen.sum())  # per-slot emitted counters
        t0 = time.perf_counter()
        for _ in range(dispatches):
            engine._decode_quantum()
        return ((int(engine._n_gen.sum()) - g0)
                / (time.perf_counter() - t0))

    seq_window(1)  # both sides warm before the paired rounds
    pairs = [(seq_window(4 if on_tpu else 8), quantum_window(1))
             for _ in range(5)]
    ratios = sorted(q / s for s, q in pairs)
    q_ratio = ratios[len(ratios) // 2]  # median
    q_tok_s = max(q for _, q in pairs)

    metric = "serving_engine_ragged_tokens_per_sec"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric, "value": round(tok_s, 1), "unit": "tok/s",
        "seq_batch1_tokens_per_sec": round(seq_tok_s, 1),
        "speedup_vs_batch1": round(tok_s / seq_tok_s, 3),
        "quantum_decode_tokens_per_sec": round(q_tok_s, 1),
        "quantum_speedup_vs_batch1": round(q_ratio, 3),
        "num_requests": len(requests), "num_slots": num_slots,
        "generated_tokens": gen,
        "mean_occupancy": round(stats.get("mean_occupancy", 0.0), 3),
        "decode_quanta": stats["decode_quanta"],
        "mixed_steps": stats["mixed_steps"],
        "arrival_req_per_s": round(req_rate, 2),
        "ttft_ms_p50": round(ttft[len(ttft) // 2], 1),
        "latency_ms_p50": round(lat[len(lat) // 2], 1),
        "latency_ms_p90": round(lat[int(len(lat) * 0.9)], 1),
        "pool_peak_blocks": stats["pool"]["peak_blocks_in_use"],
        "pool_blocks": stats["pool"]["num_blocks"],
        # the obs registry's view of the same run (ISSUE 5): histogram
        # observation counts + the trailing-window throughput gauge —
        # what a prometheus scrape of this engine would have reported
        "obs": _obs_summary(engine),
    }


def _obs_summary(engine):
    r = engine.obs.registry
    out = {
        "ttft_observations": r.get("serving_ttft_seconds").count(),
        "e2e_observations": r.get(
            "serving_e2e_latency_seconds").count(),
        "tokens_emitted": int(r.get(
            "serving_tokens_emitted_total").value()),
        "tokens_per_s_window": round(r.get(
            "serving_tokens_per_second_window").value(), 1),
        "ttft_s_p50_hist": r.get("serving_ttft_seconds").quantile(0.5),
        "metrics_exported": len(r.names()),
    }
    if engine.obs.tracer is not None:
        out["trace_events"] = len(engine.obs.tracer.events)
    return out


def serving_obs_overhead():
    """ISSUE 5 acceptance row: full instrumentation (registry + tracer)
    vs rich-hooks-off, steady-state decode-quantum throughput on the
    same model — interleaved windows, median ratio. The compiled
    quantum is the same program in both arms (fingerprint-pinned);
    only the host boundary work differs."""
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    num_slots = 8
    block_size = 32 if on_tpu else 8
    t_steps = 16 if on_tpu else 8
    plen = 16 if on_tpu else 8
    windows = 5
    max_ctx = plen + t_steps * (2 * windows + 4) + 8
    max_ctx = -(-max_ctx // block_size) * block_size
    kw = dict(num_slots=num_slots, block_size=block_size,
              prefill_chunk=plen, decode_quantum=t_steps,
              max_context=max_ctx)

    def steady(engine):
        for _ in range(num_slots):
            engine.submit(
                rng.randint(1, cfg.vocab_size, plen).astype(np.int32),
                max_new_tokens=max_ctx - plen - 4)
        while (engine.scheduler.prefilling()
               or not engine.scheduler.decoding()):
            engine.step()
        engine._decode_quantum()  # warm/compile
        return engine

    def window(engine, dispatches):
        g0 = int(engine._n_gen.sum())
        t0 = time.perf_counter()
        for _ in range(dispatches):
            engine._decode_quantum()
        return ((int(engine._n_gen.sum()) - g0)
                / (time.perf_counter() - t0))

    base = steady(ServingEngine(model, obs="off", **kw))
    inst = steady(ServingEngine(model, trace=True, **kw))
    pairs = [(window(base, 2), window(inst, 2))
             for _ in range(windows)]
    ratios = sorted(i / b for b, i in pairs)
    ratio = ratios[len(ratios) // 2]
    overhead_pct = (1.0 - ratio) * 100.0
    metric = "serving_obs_overhead_pct"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric, "value": round(overhead_pct, 2),
        "unit": "%",
        "instrumented_over_baseline": round(ratio, 4),
        "baseline_tokens_per_sec": round(
            float(np.median([b for b, _ in pairs])), 1),
        "instrumented_tokens_per_sec": round(
            float(np.median([i for _, i in pairs])), 1),
        "decode_quantum": t_steps, "num_slots": num_slots,
        "obs": _obs_summary(inst),
        "passes_3pct_bar": bool(overhead_pct < 3.0),
    }


def fault_recovery_overhead():
    """ISSUE 13 acceptance row: the resilience tier's price when
    nothing goes wrong — an engine with the full fault-containment
    machinery live (guarded dispatch wrapping every quantum, the
    watchdog calibrating per-kind deadlines after each one, pool
    accounting audited per step) but its fault injector DISARMED, vs
    the plain ``obs="off"`` engine. Interleaved windows, median
    ratio, same <3% bar as ``serving_obs_overhead``; the compiled
    quantum is the same program in both arms (fingerprint-pinned —
    the injector threads host boundaries only)."""
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    num_slots = 8
    block_size = 32 if on_tpu else 8
    t_steps = 16 if on_tpu else 8
    plen = 16 if on_tpu else 8
    windows = 5
    max_ctx = plen + t_steps * (2 * windows + 4) + 8
    max_ctx = -(-max_ctx // block_size) * block_size
    kw = dict(num_slots=num_slots, block_size=block_size,
              prefill_chunk=plen, decode_quantum=t_steps,
              max_context=max_ctx, obs="off")

    def steady(engine):
        for _ in range(num_slots):
            engine.submit(
                rng.randint(1, cfg.vocab_size, plen).astype(np.int32),
                max_new_tokens=max_ctx - plen - 4)
        while (engine.scheduler.prefilling()
               or not engine.scheduler.decoding()):
            engine.step()
        engine._decode_quantum()  # warm/compile
        return engine

    def window(engine, dispatches):
        g0 = int(engine._n_gen.sum())
        t0 = time.perf_counter()
        for _ in range(dispatches):
            engine._decode_quantum()
        return ((int(engine._n_gen.sum()) - g0)
                / (time.perf_counter() - t0))

    base = steady(ServingEngine(model, **kw))
    inst = steady(ServingEngine(model, resilience=True, **kw))
    pairs = [(window(base, 2), window(inst, 2))
             for _ in range(windows)]
    ratios = sorted(i / b for b, i in pairs)
    ratio = ratios[len(ratios) // 2]
    overhead_pct = (1.0 - ratio) * 100.0
    metric = "serving_fault_recovery_overhead_pct"
    if not on_tpu:
        metric += "_cpu_smoke"
    rep = inst.resilience_report()
    return {
        "metric": metric, "value": round(overhead_pct, 2),
        "unit": "%",
        "resilient_over_baseline": round(ratio, 4),
        "baseline_tokens_per_sec": round(
            float(np.median([b for b, _ in pairs])), 1),
        "resilient_tokens_per_sec": round(
            float(np.median([i for _, i in pairs])), 1),
        "decode_quantum": t_steps, "num_slots": num_slots,
        "faults_injected": rep["faults"]["injected_total"],
        "retries_total": rep["retries_total"],
        "watchdog_trips_total": rep["watchdog"]["trips_total"],
        "watchdog_decode_deadline_s": inst.watchdog.deadline("decode"),
        "passes_3pct_bar": bool(overhead_pct < 3.0),
    }


def attribution_overhead():
    """ISSUE 10 acceptance row: the cost ledger's price, ISOLATED —
    two fully-instrumented engines (registry + tracer, identical host
    boundary work) where the baseline arm's ledger is swapped for a
    no-op stand-in after construction, so the interleaved windows
    price exactly the per-quantum attribution bookkeeping (phase
    pro-rata + counter writes + gauge refresh). Same <3% bar as
    ``serving_obs_overhead``; the compiled quantum is the same
    program in both arms (fingerprint-pinned)."""
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    num_slots = 8
    block_size = 32 if on_tpu else 8
    t_steps = 16 if on_tpu else 8
    plen = 16 if on_tpu else 8
    windows = 5
    max_ctx = plen + t_steps * (2 * windows + 4) + 8
    max_ctx = -(-max_ctx // block_size) * block_size
    kw = dict(num_slots=num_slots, block_size=block_size,
              prefill_chunk=plen, decode_quantum=t_steps,
              max_context=max_ctx)

    class _NoLedger:
        """Same call surface as CostLedger, zero bookkeeping."""

        def configure(self, *a, **k):
            pass

        def on_quantum(self, *a, **k):
            pass

        def on_spec_round(self, *a, **k):
            pass

        def on_cached_prefill(self, *a, **k):
            pass

    def steady(engine):
        for _ in range(num_slots):
            engine.submit(
                rng.randint(1, cfg.vocab_size, plen).astype(np.int32),
                max_new_tokens=max_ctx - plen - 4)
        while (engine.scheduler.prefilling()
               or not engine.scheduler.decoding()):
            engine.step()
        engine._decode_quantum()  # warm/compile
        return engine

    def window(engine, dispatches):
        g0 = int(engine._n_gen.sum())
        t0 = time.perf_counter()
        for _ in range(dispatches):
            engine._decode_quantum()
        return ((int(engine._n_gen.sum()) - g0)
                / (time.perf_counter() - t0))

    base = ServingEngine(model, trace=True, **kw)
    base.obs.ledger = _NoLedger()
    base = steady(base)
    inst = steady(ServingEngine(model, trace=True, **kw))
    pairs = [(window(base, 2), window(inst, 2))
             for _ in range(windows)]
    ratios = sorted(i / b for b, i in pairs)
    ratio = ratios[len(ratios) // 2]
    overhead_pct = (1.0 - ratio) * 100.0
    metric = "serving_attribution_overhead_pct"
    if not on_tpu:
        metric += "_cpu_smoke"
    rep = inst.attribution()
    return {
        "metric": metric, "value": round(overhead_pct, 2),
        "unit": "%",
        "ledger_over_no_ledger": round(ratio, 4),
        "baseline_tokens_per_sec": round(
            float(np.median([b for b, _ in pairs])), 1),
        "ledger_tokens_per_sec": round(
            float(np.median([i for _, i in pairs])), 1),
        "decode_quantum": t_steps, "num_slots": num_slots,
        "useful_token_fraction": round(
            rep["useful_token_fraction"], 4),
        "attributed_tokens": int(rep["attributed_tokens_total"]),
        "obs": _obs_summary(inst),
        "passes_3pct_bar": bool(overhead_pct < 3.0),
    }


def slo_overhead():
    """ISSUE 6 acceptance row: the operability tier's price — an
    engine with SLO evaluation + the flight recorder on (burn-rate
    health computed after EVERY dispatch, the consumption pattern of a
    shedding scheduler, plus per-request journaling) vs ``obs="off"``,
    steady-state decode-quantum throughput, interleaved windows,
    median ratio, same <3% bar as ``serving_obs_overhead``. The
    compiled quantum is the same program in both arms
    (fingerprint-pinned); only host boundary work differs."""
    from paddle_tpu.obs import FlightRecorder
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    num_slots = 8
    block_size = 32 if on_tpu else 8
    t_steps = 16 if on_tpu else 8
    plen = 16 if on_tpu else 8
    windows = 5
    max_ctx = plen + t_steps * (2 * windows + 4) + 8
    max_ctx = -(-max_ctx // block_size) * block_size
    kw = dict(num_slots=num_slots, block_size=block_size,
              prefill_chunk=plen, decode_quantum=t_steps,
              max_context=max_ctx)

    def steady(engine):
        for _ in range(num_slots):
            engine.submit(
                rng.randint(1, cfg.vocab_size, plen).astype(np.int32),
                max_new_tokens=max_ctx - plen - 4)
        while (engine.scheduler.prefilling()
               or not engine.scheduler.decoding()):
            engine.step()
        engine._decode_quantum()  # warm/compile
        return engine

    def window(engine, dispatches, evaluate=False):
        g0 = int(engine._n_gen.sum())
        t0 = time.perf_counter()
        for _ in range(dispatches):
            engine._decode_quantum()
            if evaluate:
                engine.health()  # the shedder's per-quantum poll
        return ((int(engine._n_gen.sum()) - g0)
                / (time.perf_counter() - t0))

    base = steady(ServingEngine(model, obs="off", **kw))
    # e2e threshold 0 -> every retiring request dumps its journal, so
    # the anomaly-capture path is in the priced loop, not just armed
    inst = steady(ServingEngine(
        model, slo=True, flight=FlightRecorder(e2e_threshold=1e-9),
        **kw))
    pairs = [(window(base, 2), window(inst, 2, evaluate=True))
             for _ in range(windows)]
    ratios = sorted(i / b for b, i in pairs)
    ratio = ratios[len(ratios) // 2]
    overhead_pct = (1.0 - ratio) * 100.0
    report = inst.health()
    # drain to retirement so the forced e2e trigger actually exercises
    # the anomaly-capture + JSONL path inside this row (steady-state
    # windows never retire a slot)
    while inst.has_work:
        inst.step()
    assert inst.flight.captured_total == num_slots, \
        "every retirement must have dumped a journal"
    metric = "serving_slo_overhead_pct"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric, "value": round(overhead_pct, 2),
        "unit": "%",
        "instrumented_over_baseline": round(ratio, 4),
        "baseline_tokens_per_sec": round(
            float(np.median([b for b, _ in pairs])), 1),
        "instrumented_tokens_per_sec": round(
            float(np.median([i for _, i in pairs])), 1),
        "decode_quantum": t_steps, "num_slots": num_slots,
        "slo_state": report["state"],
        "slo_objectives": len(report["objectives"]),
        "health_evals_timed": 2 * windows,
        "flight": inst.flight.stats(),
        "passes_3pct_bar": bool(overhead_pct < 3.0),
    }


def serving_overload():
    """ISSUE 7 acceptance row: p95 TTFT + shed rate under a >capacity
    Poisson burst through the front door, with and without shedding.
    The shed arm's admitted-TTFT tail must stay bounded (queue
    backpressure + SLO-burn-rate admission keep the queue short;
    priority preemption keeps INTERACTIVE ahead) while the no-shed arm
    admits everything and its tail grows with the backlog."""
    from paddle_tpu.obs.slo import SLOSet, default_serving_slos
    from paddle_tpu.serving import (
        BATCH, INTERACTIVE, NORMAL, FrontDoorPolicy, ServingEngine,
        ServingFrontDoor, no_shed_policy,
    )

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    if on_tpu:
        num_slots, block_size, t_steps, n_req = 8, 32, 16, 96
        p_lo, p_hi, n_lo, n_hi = 32, 128, 16, 64
        ttft_thr, overload = 0.5, 3.0
    else:
        num_slots, block_size, t_steps, n_req = 4, 8, 4, 48
        p_lo, p_hi, n_lo, n_hi = 4, 12, 4, 12
        ttft_thr, overload = 0.25, 3.0
    p_lens = np.exp(rng.uniform(np.log(p_lo), np.log(p_hi),
                                n_req)).astype(int)
    n_news = np.exp(rng.uniform(np.log(n_lo), np.log(n_hi),
                                n_req)).astype(int)
    # deterministic class mix: ~20% INTERACTIVE, 50% NORMAL, 30% BATCH
    classes = [INTERACTIVE, NORMAL, BATCH, NORMAL, BATCH,
               NORMAL, INTERACTIVE, NORMAL, BATCH, NORMAL]
    requests = [(rng.randint(1, cfg.vocab_size, int(p)).astype(np.int32),
                 int(n), classes[i % len(classes)])
                for i, (p, n) in enumerate(zip(p_lens, n_news))]
    mean_new = float(np.mean([n for _, n, _ in requests]))
    max_ctx = max(p.shape[0] + n for p, n, _ in requests)
    max_ctx = -(-max_ctx // block_size) * block_size

    def build_door(shed):
        engine = ServingEngine(
            model, num_slots=num_slots, block_size=block_size,
            prefill_chunk=128 if on_tpu else 8,
            decode_quantum=t_steps, max_context=max_ctx,
            slo=SLOSet(default_serving_slos(ttft_p95_s=ttft_thr)),
            flight=True)
        # NORMAL rides backpressure rather than the burn-rate gate
        # here (shed_on_critical keeps only BATCH): under a sustained
        # burst the TTFT objective pins critical for the whole run, and
        # the stock ladder would admit ONLY interactive traffic — no
        # lower-priority victim would ever hold a slot, hiding the
        # preemption tier this row is also meant to exercise
        policy = (FrontDoorPolicy(shed_on_warn=(BATCH,),
                                  shed_on_critical=(BATCH,),
                                  max_waiting=2 * num_slots,
                                  preempt=True)
                  if shed else no_shed_policy(preempt=False))
        return ServingFrontDoor(engine, policy)

    # calibrate engine token capacity on a warm door (also compiles
    # the quantum + mixed shapes both arms reuse via the same model)
    calib = build_door(shed=False)
    for p, n, _ in requests[:num_slots]:
        calib.submit(p, max_new_tokens=n)
    calib.run_until_idle()  # compile pass
    for p, n, _ in requests[:num_slots]:
        calib.submit(p, max_new_tokens=n)
    t0 = time.perf_counter()
    calib.run_until_idle()
    # both passes land in `completed`; only the second one is timed
    calib_tok_s = (sum(len(r.tokens) for r in calib.engine.completed)
                   / 2.0 / (time.perf_counter() - t0))
    log(f"calibrated capacity ~{calib_tok_s:.0f} tok/s; offering "
        f"{overload:.1f}x")

    req_rate = overload * calib_tok_s / mean_new
    gaps = rng.exponential(1.0 / req_rate, n_req)
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0

    def run_arm(shed):
        door = build_door(shed)
        eng = door.engine
        # warm this arm's quantum closure, then reset every surface
        for p, n, _ in requests[:num_slots]:
            door.submit(p, max_new_tokens=n)
        door.run_until_idle()
        eng.completed.clear()
        eng.obs.reset()
        submitted = 0
        t0 = time.perf_counter()
        while submitted < n_req or eng.has_work:
            now = time.perf_counter() - t0
            while submitted < n_req and arrivals[submitted] <= now:
                p, n, pr = requests[submitted]
                door.submit(p, max_new_tokens=n, priority=pr)
                submitted += 1
            if eng.has_work:
                door.pump()
            elif submitted < n_req:
                time.sleep(min(arrivals[submitted] - now, 0.01))
        drain = door.drain()
        wall = time.perf_counter() - t0
        done = eng.completed
        ttft = sorted((r.first_token_time - r.arrival_time) * 1e3
                      for r in done if r.first_token_time is not None)
        e2e = sorted((r.finish_time - r.arrival_time) * 1e3
                     for r in done)
        by_class = {}
        for name, pri in (("interactive", INTERACTIVE),
                          ("normal", NORMAL), ("batch", BATCH)):
            ts = sorted((r.first_token_time - r.arrival_time) * 1e3
                        for r in done if r.priority == pri
                        and r.first_token_time is not None)
            if ts:
                by_class[name] = {
                    "n": len(ts),
                    "ttft_ms_p50": round(ts[len(ts) // 2], 1),
                    "ttft_ms_p95": round(ts[int(len(ts) * 0.95)], 1),
                }
        shed_n = len(door.shed_requests)
        reasons = {}
        # NB: `if eng.flight` would hit FlightRecorder.__len__ (0 live
        # journals after drain) — identity check, not truthiness
        for rec in (eng.flight.records()
                    if eng.flight is not None else []):
            ev = rec["events"][-1]
            if ev["kind"] == "shed":
                reasons[ev["reason"]] = reasons.get(ev["reason"], 0) + 1
        return {
            "shedding": bool(shed),
            "completed": len(done), "shed": shed_n,
            "shed_rate": round(shed_n / n_req, 3),
            "shed_by_reason": reasons,
            "preempted": eng.scheduler.preempted_total,
            "resumed": eng.scheduler.resumed_total,
            "ttft_ms_p50": round(ttft[len(ttft) // 2], 1),
            "ttft_ms_p95": round(ttft[int(len(ttft) * 0.95)], 1),
            "ttft_by_class": by_class,
            "e2e_ms_p95": round(e2e[int(len(e2e) * 0.95)], 1),
            "tok_s": round(sum(len(r.tokens) for r in done) / wall, 1),
            "health_final": eng.health()["state"],
            "drain": {k: drain[k] for k in
                      ("completed", "shed", "preempted", "resumed")},
            "wall_s": round(wall, 2),
        }

    shed_arm = run_arm(True)
    noshed_arm = run_arm(False)
    metric = "serving_overload_noshed_over_shed_p95_ttft"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric,
        "value": round(noshed_arm["ttft_ms_p95"]
                       / max(shed_arm["ttft_ms_p95"], 1e-9), 2),
        "unit": "x",
        "overload_factor": overload,
        "offered_req_per_s": round(req_rate, 2),
        "calibrated_capacity_tok_s": round(calib_tok_s, 1),
        "ttft_slo_s": ttft_thr,
        "num_requests": n_req, "num_slots": num_slots,
        "shed_arm": shed_arm, "no_shed_arm": noshed_arm,
        "shed_bounds_p95_ttft": bool(
            shed_arm["ttft_ms_p95"] < noshed_arm["ttft_ms_p95"]),
    }


def shared_prefix():
    """ISSUE 9 acceptance row: content-addressed prefix caching under
    ragged Poisson arrivals over one common system prompt — shared
    (``prefix_cache=True``) vs unshared arms on the SAME arrival
    trace, plus a deterministic simultaneous-burst residency probe.
    Claims: prefill tokens, prefill latency (admit -> first token) and
    novel pool residency scale with UNIQUE tokens; streams
    bit-identical either way (a couple of exact-system-prompt requests
    force the copy-on-write path inside the measured run)."""
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    if on_tpu:
        num_slots, block_size, t_steps, chunk = 8, 32, 16, 128
        n_req, sys_len = 32, 256          # 8 full cache blocks shared
        u_lo, u_hi, n_lo, n_hi = 16, 96, 16, 64
    else:
        num_slots, block_size, t_steps, chunk = 4, 8, 4, 8
        n_req, sys_len = 12, 16           # 2 full cache blocks shared
        u_lo, u_hi, n_lo, n_hi = 2, 8, 4, 10

    # one common system prompt + log-ragged unique tails (the
    # shared-assistant traffic shape the cache targets); every 6th
    # request is the BARE system prompt — a full-chain hit whose capped
    # one-token re-prefill lands in a shared block, so copy-on-write
    # fires inside the measured (parity-checked) run
    sys_prompt = rng.randint(1, cfg.vocab_size, sys_len).astype(np.int32)
    u_lens = np.exp(rng.uniform(np.log(u_lo), np.log(u_hi),
                                n_req)).astype(int)
    n_news = np.exp(rng.uniform(np.log(n_lo), np.log(n_hi),
                                n_req)).astype(int)
    requests = []
    for i, (u, n) in enumerate(zip(u_lens, n_news)):
        if i % 6 == 5:
            requests.append((sys_prompt.copy(), int(n)))
        else:
            requests.append((np.concatenate([
                sys_prompt,
                rng.randint(1, cfg.vocab_size, int(u))
                .astype(np.int32)]), int(n)))
    # the residency probe's burst: num_slots fresh tails, submitted
    # simultaneously so all slots are resident at once
    burst = [(np.concatenate([
        sys_prompt,
        rng.randint(1, cfg.vocab_size, int(u_hi)).astype(np.int32)]),
        int(n_lo)) for _ in range(num_slots)]
    max_ctx = max(p.shape[0] + n for p, n in requests + burst)
    max_ctx = -(-max_ctx // block_size) * block_size
    # a generous pool (2x the slot-saturated demand): residency is
    # MEASURED, not clipped — with the default sizing both arms would
    # just park at the pool ceiling and the high-water mark says
    # nothing about sharing
    pool_blocks = 2 * num_slots * (max_ctx // block_size) + 1

    # warmup prompts are DISTINCT random ids at the same lengths: they
    # compile the quantum + mixed-step shapes without handing the
    # shared arm a pre-seeded system prompt
    wrng = np.random.RandomState(7)
    warm = [(wrng.randint(1, cfg.vocab_size, p.shape[0])
             .astype(np.int32), n) for p, n in requests[:num_slots]]

    def run_arm(prefix, arrivals):
        engine = ServingEngine(
            model, num_slots=num_slots, block_size=block_size,
            num_blocks=pool_blocks, prefill_chunk=chunk,
            decode_quantum=t_steps, max_context=max_ctx,
            prefix_cache=prefix)
        for p, n in warm:
            engine.submit(p, max_new_tokens=n)
        engine.run()
        engine.completed.clear()
        engine.obs.reset()
        if prefix:
            engine.pool.clear_prefix_cache()  # drop warmup entries
        # re-arm the residency high-water mark so peak_blocks_in_use
        # measures the timed phase only
        engine.pool._peak_blocks = engine.pool.blocks_in_use

        submitted = 0
        t0 = time.perf_counter()
        while submitted < n_req or engine.has_work:
            now = time.perf_counter() - t0
            while submitted < n_req and arrivals[submitted] <= now:
                p, n = requests[submitted]
                engine.submit(p, max_new_tokens=n,
                              req_id=f"r{submitted}")
                submitted += 1
            if engine.has_work:
                engine.step()
            elif submitted < n_req:
                time.sleep(min(arrivals[submitted] - now, 0.01))
        wall = time.perf_counter() - t0
        st = engine.engine_stats()
        done = list(engine.completed)
        ttft = sorted((r.first_token_time - r.arrival_time) * 1e3
                      for r in done)
        # admit -> first token isolates the PREFILL latency the cache
        # attacks from queue wait (which tracks offered load, not
        # sharing): aliased blocks skip their prefill chunks entirely
        pfl = sorted((r.first_token_time - r.admit_time) * 1e3
                     for r in done)
        out = {
            "prefill_tokens": st["prefill_tokens"],
            "generated_tokens": st["generated_tokens"],
            "peak_blocks": st["pool"]["peak_blocks_in_use"],
            "pool_blocks": st["pool"]["num_blocks"],
            "ttft_ms_p50": round(ttft[len(ttft) // 2], 1),
            "ttft_ms_p95": round(ttft[int(len(ttft) * 0.95)], 1),
            "prefill_latency_ms_p50": round(pfl[len(pfl) // 2], 1),
            "prefill_latency_ms_p95": round(
                pfl[int(len(pfl) * 0.95)], 1),
            "tok_s": round(st["generated_tokens"] / wall, 1),
            "wall_s": round(wall, 2),
        }
        streams = {str(r.req_id): list(r.tokens) for r in done}

        # residency probe: all slots resident at once on fresh tails
        # (the shared arm's system-prompt blocks count ONCE across the
        # whole burst; the unshared arm pays them per slot)
        engine.pool._peak_blocks = engine.pool.blocks_in_use
        for i, (p, n) in enumerate(burst):
            engine.submit(p, max_new_tokens=n, req_id=f"b{i}")
        engine.run()
        out["burst_peak_blocks"] = \
            engine.pool.fragmentation_stats()["peak_blocks_in_use"]
        if prefix:
            out["prefix_cache"] = engine.pool.prefix_cache_stats()
            out["cached_prompt_tokens"] = sum(
                r.cached_prefix_tokens for r in engine.completed)
        for r in engine.completed[len(done):]:
            streams[str(r.req_id)] = list(r.tokens)
        return out, streams

    # calibrate offered load off a closed warm pass, then offer ~0.75x
    # of it: the queue stays shallow, so TTFT reflects prefill work,
    # and arrivals still overlap enough that hits land while peers are
    # live (the cache survives retirement anyway — the index holds
    # published blocks at refcount 1)
    cal = ServingEngine(model, num_slots=num_slots,
                        block_size=block_size, num_blocks=pool_blocks,
                        prefill_chunk=chunk, decode_quantum=t_steps,
                        max_context=max_ctx)
    for p, n in warm:
        cal.submit(p, max_new_tokens=n)
    cal.run()  # compile pass
    for p, n in warm:
        cal.submit(p, max_new_tokens=n)
    t0 = time.perf_counter()
    cal.run()
    cal_tok_s = (sum(n for _, n in warm)
                 / (time.perf_counter() - t0))
    mean_new = float(np.mean([n for _, n in requests]))
    req_rate = 0.75 * cal_tok_s / mean_new
    gaps = rng.exponential(1.0 / req_rate, n_req)
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0
    log(f"calibrated ~{cal_tok_s:.0f} tok/s; offering "
        f"{req_rate:.1f} req/s on {n_req} requests")

    shared, s_streams = run_arm(True, arrivals)
    unshared, u_streams = run_arm(False, arrivals)
    assert s_streams == u_streams, \
        "prefix-cached streams must be bit-identical to unshared"

    prompt_tokens = int(sum(p.shape[0] for p, _ in requests))
    unique_tokens = int(sys_len + sum(
        int(u) for i, u in enumerate(u_lens) if i % 6 != 5))
    metric = "serving_prefix_unshared_over_shared_prefill_tokens"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric,
        "value": round(unshared["prefill_tokens"]
                       / max(shared["prefill_tokens"], 1), 3),
        "unit": "x",
        "prefill_latency_p50_unshared_over_shared": round(
            unshared["prefill_latency_ms_p50"]
            / max(shared["prefill_latency_ms_p50"], 1e-9), 3),
        "ttft_p50_unshared_over_shared": round(
            unshared["ttft_ms_p50"] / max(shared["ttft_ms_p50"], 1e-9),
            3),
        "burst_peak_blocks_unshared_over_shared": round(
            unshared["burst_peak_blocks"]
            / max(shared["burst_peak_blocks"], 1), 3),
        "num_requests": n_req, "num_slots": num_slots,
        "system_prompt_tokens": sys_len, "block_size": block_size,
        "prompt_tokens_total": prompt_tokens,
        "unique_prompt_tokens": unique_tokens,
        "arrival_req_per_s": round(req_rate, 2),
        "shared_arm": shared, "unshared_arm": unshared,
        "streams_bit_identical": True,
    }


def serving_tp():
    """ISSUE 11 acceptance row: the TP-sharded quantum family — the
    SAME weights and ragged request set through a tp=1 and a tp=2
    engine (CPU virtual devices off-TPU; both arms share one physical
    core, so wall time rides along but the CLAIM is structural).
    Guarded metric: per-chip KV pool residency ratio tp1/tp2 at a
    deterministic allocation point — exactly 2.0 when the pool really
    carries the kv-head split, decaying to 1.0 if a refactor drops the
    NamedSharding (the runtime twin of the serving_tp_step recipe's
    min_sharded_params gate). Streams must be bit-identical; mean
    decode-quantum ms and the build-time collective census ride
    along."""
    import jax
    from paddle_tpu.serving import ServingEngine

    if jax.device_count() < 2:
        raise RuntimeError(
            "serving_tp needs >=2 visible devices — on CPU set "
            "XLA_FLAGS='--xla_force_host_platform_device_count=2' "
            "before jax initializes")
    cfg, on_tpu = _serving_cfg()
    cfg.tensor_parallel = True  # mp layers init serial-identical
    rng = np.random.RandomState(0)
    requests = _request_set(cfg, on_tpu, rng)
    if on_tpu:
        num_slots, block_size, quantum, chunk = 8, 32, 16, 128
    else:
        num_slots, block_size, quantum, chunk = 4, 8, 8, 8

    def run_arm(tp):
        model = _build_model(cfg, on_tpu)
        eng = ServingEngine(model, num_slots=num_slots,
                            block_size=block_size, prefill_chunk=chunk,
                            decode_quantum=quantum,
                            **({"tp": tp} if tp > 1 else {}))
        for p, n in requests[:2]:
            eng.submit(p, max_new_tokens=n)
        eng.run()  # compile pass (tp2's quantum is AOT from build)
        eng.obs.reset()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=n) for p, n in requests]
        # one step admits a full slate: residency is read at the same
        # deterministic allocation point in both arms
        eng.step()
        resid = eng.pool.bytes_in_use()
        resid_chip = eng.pool.per_chip_bytes_in_use()
        eng.run()
        wall = time.perf_counter() - t0
        h = eng.obs.registry.get("serving_quantum_seconds")
        arm = {
            "tp": tp,
            "tok_s": round(sum(n for _, n in requests) / wall, 1),
            "wall_s": round(wall, 2),
            "decode_quantum_ms_mean": round(
                1e3 * h.sum(kind="decode")
                / max(h.count(kind="decode"), 1), 2),
            "pool_bytes_step1": int(resid),
            "pool_bytes_per_chip_step1": int(resid_chip),
            "pool_shards": eng.pool.tp_shards,
            "collective_ops_per_quantum":
                eng.quantum_collectives["count_total"],
            "collective_bytes_per_quantum":
                eng.quantum_collectives["bytes_total"],
        }
        return arm, [list(map(int, eng.output_tokens(r)))
                     for r in reqs]

    tp1, s1 = run_arm(1)
    tp2, s2 = run_arm(2)
    assert s1 == s2, "tp2 streams must be bit-identical to tp1"
    metric = "serving_tp_per_chip_pool_residency_ratio"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric,
        "value": round(tp1["pool_bytes_per_chip_step1"]
                       / max(tp2["pool_bytes_per_chip_step1"], 1), 3),
        "unit": "x",
        "quantum_ms_tp2_over_tp1": round(
            tp2["decode_quantum_ms_mean"]
            / max(tp1["decode_quantum_ms_mean"], 1e-9), 3),
        "num_requests": len(requests),
        "num_slots": num_slots, "block_size": block_size,
        "devices_visible": jax.device_count(),
        "streams_bit_identical": True,
        "tp1_arm": tp1, "tp2_arm": tp2,
    }


def serving_int8():
    """ISSUE 14 acceptance row: the quantized quantum family — the
    SAME ragged request set through (a) a float engine holding the
    DEQUANTIZED int8 matrices (``dequant(quant(w))`` — the exact
    floats the int8 kernel's fused dequant feeds its matmuls), (b) a
    weight-only-int8 engine, and (c) a fully quantized engine (int8
    weights + int8 KV pool with per-row f32 scale pools). The
    weight-only arm must match the dequant arm BIT-FOR-BIT — stream
    equality, not tolerance (asserted off-TPU where params are f32;
    recorded on TPU where bf16 storage rounds the oracle). Guarded
    metric: KV pool residency float/int8 at a deterministic
    allocation point (full slate admitted, read after one step) —
    exactly (4d)/(d+4) by construction when the pool really stores
    int8 rows + f32 scales (3.2x at the smoke's head_dim 16),
    decaying to 1.0 on a silent float fallback. Decode-quantum ms and
    the int8-KV arm's stream agreement vs the weight-only arm ride
    along (per-row KV scales perturb logits within quantization
    error; agreement is informational, not the claim)."""
    import paddle_tpu as paddle
    from paddle_tpu.nn.layer.common import Linear
    from paddle_tpu.nn.quant import weight_quantize
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    rng = np.random.RandomState(0)
    requests = _request_set(cfg, on_tpu, rng)
    if on_tpu:
        num_slots, block_size, quantum, chunk = 8, 32, 16, 128
    else:
        num_slots, block_size, quantum, chunk = 4, 8, 8, 8

    def dequantize_in_place(layer):
        # the oracle arm: every Linear weight becomes the float matrix
        # the quantized kernel reconstructs inside its matmul
        for sub in layer._sub_layers.values():
            if isinstance(sub, Linear):
                qw, ws = weight_quantize(sub.weight)
                deq = (np.asarray(qw._value).astype(np.float32)
                       * np.asarray(ws._value)[None, :])
                sub.weight.set_value(paddle.to_tensor(
                    deq.astype(np.asarray(sub.weight._value).dtype)))
            else:
                dequantize_in_place(sub)

    def run_arm(name):
        model = _build_model(cfg, on_tpu)
        kw = {}
        if name == "dequant_float":
            dequantize_in_place(model)
        elif name == "w8":
            kw = dict(quantize="weight_only_int8")
        elif name == "w8kv8":
            kw = dict(quantize="weight_only_int8", kv_dtype="int8")
        eng = ServingEngine(model, num_slots=num_slots,
                            block_size=block_size, prefill_chunk=chunk,
                            decode_quantum=quantum, **kw)
        for p, n in requests[:2]:
            eng.submit(p, max_new_tokens=n)
        eng.run()  # compile pass
        eng.obs.reset()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=n) for p, n in requests]
        # one step admits a full slate: residency is read at the same
        # deterministic allocation point in every arm (block demand is
        # set by prompt lengths, not weight values)
        eng.step()
        resid = eng.pool.bytes_in_use()
        eng.run()
        wall = time.perf_counter() - t0
        h = eng.obs.registry.get("serving_quantum_seconds")
        arm = {
            "arm": name,
            "tok_s": round(sum(n for _, n in requests) / wall, 1),
            "wall_s": round(wall, 2),
            "decode_quantum_ms_mean": round(
                1e3 * h.sum(kind="decode")
                / max(h.count(kind="decode"), 1), 2),
            "pool_bytes_step1": int(resid),
            "kv_dtype": eng.pool.fragmentation_stats()["kv_dtype"],
            "pool_quantized": bool(eng.pool.quantized),
        }
        return arm, [list(map(int, eng.output_tokens(r)))
                     for r in reqs]

    deq, s_deq = run_arm("dequant_float")
    w8, s_w8 = run_arm("w8")
    q, s_q = run_arm("w8kv8")
    oracle_exact = s_w8 == s_deq
    if not on_tpu:
        assert oracle_exact, ("weight-only-int8 streams must equal "
                              "the dequantized-float oracle")
    agreement = sum(a == b for a, b in zip(s_q, s_w8)) / len(s_q)
    metric = "serving_int8_pool_residency_ratio"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric,
        "value": round(deq["pool_bytes_step1"]
                       / max(q["pool_bytes_step1"], 1), 3),
        "unit": "x",
        "weight_oracle_streams_bit_identical": bool(oracle_exact),
        "kv_int8_stream_agreement": round(agreement, 3),
        "quantum_ms_int8_over_float": round(
            q["decode_quantum_ms_mean"]
            / max(deq["decode_quantum_ms_mean"], 1e-9), 3),
        "num_requests": len(requests),
        "num_slots": num_slots, "block_size": block_size,
        "float_arm": deq, "w8_arm": w8, "w8kv8_arm": q,
    }


def speculative_decode():
    """VERDICT weak #1: speculative greedy decode tok/s vs the
    single-dispatch loop, with acceptance rate — both the realistic
    (independent narrow draft, near-floor acceptance) and the ceiling
    (draft=target, acceptance=1) arms."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nlp.generation import (
        generate_on_device, speculative_greedy_search,
    )

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    if on_tpu:
        draft_cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=2, num_attention_heads=8,
            num_key_value_heads=8, max_position_embeddings=2048,
            tensor_parallel=False)
        prompt, new, gamma = 128, 128, 4
    else:
        draft_cfg = LlamaConfig.tiny(tensor_parallel=False)
        prompt, new, gamma = 8, 8, 4
    paddle.seed(1)
    draft = LlamaForCausalLM(draft_cfg)
    if on_tpu:
        draft.astype("bfloat16")
    draft.eval()

    ids = paddle.to_tensor(np.random.RandomState(3).randint(
        1, cfg.vocab_size, (1, prompt)))

    def time_it(fn, iters=3):
        fn()  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        return (time.perf_counter() - t0) / iters, out

    dt_dev, _ = time_it(lambda: np.asarray(generate_on_device(
        model, ids, max_new_tokens=new)._value))
    dt_spec, (toks, acc) = time_it(lambda: speculative_greedy_search(
        model, draft, ids, max_new_tokens=new, gamma=gamma))
    # ceiling arm: the draft IS the target -> every proposal accepted;
    # isolates the host-loop + verify-forward overhead from mispredicts
    dt_self, (_, acc_self) = time_it(lambda: speculative_greedy_search(
        model, model, ids, max_new_tokens=new, gamma=gamma))

    return {
        "metric": "speculative_decode_speedup_vs_ondevice",
        "value": round(dt_dev / dt_spec, 3), "unit": "x",
        "ondevice_tokens_per_sec": round(new / dt_dev, 1),
        "spec_tokens_per_sec": round(new / dt_spec, 1),
        "acceptance_rate": round(float(acc), 3),
        "selfdraft_speedup": round(dt_dev / dt_self, 2),
        "selfdraft_acceptance": round(float(acc_self), 3),
        "gamma": gamma, "new_tokens": new,
        "draft_params_ratio": "h1024L2 vs h4096L4" if on_tpu
        else "tiny vs tiny",
    }


def _spec_pair(on_tpu, num_layers_draft, eps):
    """Build the stand-in draft/target pair: the draft shares the
    target's embedding / first ``num_layers_draft`` layers / final norm
    / lm head, and the target's TAIL layers have their o_proj/down_proj
    scaled by ``eps`` (low-gain tail) so acceptance lands in the
    trained-pair regime — random-init weights cannot produce it any
    other way. The target still pays its full depth per forward."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        kw = dict(vocab_size=32000, hidden_size=4096,
                  intermediate_size=11008, num_attention_heads=32,
                  num_key_value_heads=8, max_position_embeddings=2048,
                  tensor_parallel=False)
        num_layers = 4
    else:
        kw = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_attention_heads=4, num_key_value_heads=2,
                  max_position_embeddings=1024, tensor_parallel=False)
        num_layers = 6
    paddle.seed(0)
    target = LlamaForCausalLM(
        LlamaConfig(num_hidden_layers=num_layers, **kw))
    for i in range(num_layers_draft, num_layers):
        layer = target.llama.layers[i]
        for lin in (layer.self_attn.o_proj, layer.mlp.down_proj):
            lin.weight.set_value(lin.weight.numpy() * eps)
    paddle.seed(1)
    draft = LlamaForCausalLM(
        LlamaConfig(num_hidden_layers=num_layers_draft, **kw))
    tsd = target.state_dict()
    for k, v in draft.state_dict().items():
        if k in tsd and tuple(tsd[k].shape) == tuple(v.shape):
            v.set_value(tsd[k].numpy())
    # honest floor: an INDEPENDENT random-init draft of the same shape
    paddle.seed(2)
    indep = LlamaForCausalLM(
        LlamaConfig(num_hidden_layers=num_layers_draft, **kw))
    for m in (target, draft, indep):
        if on_tpu:
            m.astype("bfloat16")
        m.eval()
    return target, draft, indep, num_layers


def speculative_serving():
    """ISSUE 3 acceptance row: on-device speculative serving vs the
    plain decode quantum — steady-state decode capacity (all slots
    live, interleaved timing windows, median ratio), acceptance rate
    and dispatch decomposition for both draft arms."""
    import jax
    from paddle_tpu.serving import ServingEngine

    on_tpu = jax.default_backend() == "tpu"
    gamma = 8
    num_slots = 8
    ld = 1
    target, draft, indep, n_layers = _spec_pair(on_tpu, ld, eps=0.01)
    cfg = target.config
    # wide tables = the gather/KV-read-bound regime speculation targets
    # (verify amortizes the per-position KV read over gamma+1 tokens)
    max_ctx, block_size, plen = ((1792, 32, 128) if on_tpu
                                 else (768, 32, 64))
    t_steps = 8
    rng = np.random.RandomState(0)

    def steady(engine):
        for _ in range(num_slots):
            engine.submit(
                rng.randint(1, cfg.vocab_size, plen).astype(np.int32),
                max_new_tokens=max_ctx - plen - gamma - 4)
        while (engine.scheduler.prefilling()
               or not engine.scheduler.decoding()):
            engine.step()
        engine._decode_quantum()  # warm/compile
        return engine

    def window(engine, dispatches):
        g0 = int(engine._n_gen.sum())
        t0 = time.perf_counter()
        for _ in range(dispatches):
            engine._decode_quantum()
        return ((int(engine._n_gen.sum()) - g0)
                / (time.perf_counter() - t0))

    plain = ServingEngine(target, num_slots=num_slots,
                          block_size=block_size, decode_quantum=t_steps,
                          max_context=max_ctx, prefill_chunk=plen)
    steady(plain)

    def spec_arm(d_model):
        spec = ServingEngine(target, spec_draft=d_model,
                             spec_gamma=gamma, num_slots=num_slots,
                             block_size=block_size, max_context=max_ctx,
                             prefill_chunk=plen)
        steady(spec)
        pairs = [(window(plain, 2), window(spec, 2)) for _ in range(5)]
        ratios = sorted(q / s for s, q in pairs)
        st = spec.engine_stats()
        yield_slot = (st["quantum_tokens"]
                      / max(st["spec_rounds"] * num_slots, 1))
        return {
            "speedup_vs_plain_quantum": round(
                ratios[len(ratios) // 2], 3),
            "spec_tokens_per_sec": round(
                float(np.median([q for _, q in pairs])), 1),
            "plain_tokens_per_sec": round(
                float(np.median([s for s, _ in pairs])), 1),
            "acceptance_rate": round(st["spec_acceptance_rate"], 3),
            "tokens_per_round_per_slot": round(yield_slot, 2),
            # dispatch decomposition (per emitted token, per slot):
            # plain = 1 target forward; spec = 1/yield verify forwards
            # + (gamma+1)/yield draft forwards, all in ONE dispatch
            "target_forwards_per_token": round(1.0 / yield_slot, 3),
            "draft_forwards_per_token": round(
                (gamma + 1) / yield_slot, 3),
        }

    standin = spec_arm(draft)
    floor = spec_arm(indep)
    metric = "speculative_serving_speedup_vs_plain_quantum"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric, "value": standin["speedup_vs_plain_quantum"],
        "unit": "x", "gamma": gamma, "num_slots": num_slots,
        "max_context": max_ctx,
        "plain_decode_quantum": t_steps,
        "plain_target_forwards_per_token": 1.0,
        "standin_arm": standin, "independent_draft_arm": floor,
        "draft_target_pair": (
            f"stand-in: L{ld} draft sharing embed/first-layer/norm/head "
            f"of the L{n_layers} target (tail o_proj/down_proj x0.01); "
            f"independent arm: random-init L{ld} draft"),
    }


def serving_cluster():
    """ISSUE 15 acceptance row: the cluster tier — (a) prefix-affinity
    routing vs the round-robin control on a multi-tenant
    shared-system-prompt trace (router hit-rate + aggregate cached
    prompt tokens), (b) admitted-throughput scaling replicas 1->4
    under per-door backpressure with cluster shed coordination. Both
    guarded ratios are deterministic: routing is a pure host function
    of the trace and admission depends only on queue depths at the
    submission points, so no noise band. Cluster-of-4 streams are
    asserted bit-identical to cluster-of-1 (and to the round-robin
    arm) inside the row."""
    from paddle_tpu.serving import (
        ClusterFrontDoor, ClusterReplica, ClusterRouter,
        FrontDoorPolicy, ServingEngine, no_shed_policy)

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    if on_tpu:
        num_slots, block_size, t_steps, chunk = 4, 32, 8, 64
        n_tenants, per_tenant, sys_blocks = 6, 6, 4
        tail_lo, tail_hi, n_new = 8, 32, 16
        n_scale, scale_prompt, scale_new, max_wait = 96, 48, 16, 2
    else:
        num_slots, block_size, t_steps, chunk = 2, 8, 4, 8
        n_tenants, per_tenant, sys_blocks = 6, 4, 2
        tail_lo, tail_hi, n_new = 2, 6, 4
        n_scale, scale_prompt, scale_new, max_wait = 40, 10, 4, 2

    # tenant-interleaved arrivals (t0r0 t1r0 ... t0r1 ...): every
    # tenant's LATER requests re-land where its system prompt is hot
    # under affinity, while round-robin walks each tenant across
    # replicas and pays the cold prefill per replica it touches
    sys_len = sys_blocks * block_size
    tenants = [rng.randint(1, cfg.vocab_size, sys_len).astype(np.int32)
               for _ in range(n_tenants)]
    prompts = []
    for _ in range(per_tenant):
        for t in range(n_tenants):
            tail = rng.randint(1, cfg.vocab_size,
                               int(rng.randint(tail_lo, tail_hi + 1))
                               ).astype(np.int32)
            prompts.append(np.concatenate([tenants[t], tail]))
    max_ctx = max(int(p.shape[0]) for p in prompts) + max(
        n_new, scale_new)
    max_ctx = max(max_ctx, scale_prompt + scale_new)
    max_ctx = -(-max_ctx // block_size) * block_size
    pool_blocks = 2 * num_slots * (max_ctx // block_size) + 1
    wrng = np.random.RandomState(7)

    def mk_cluster(n, strategy, policy):
        reps = []
        for i in range(n):
            eng = ServingEngine(
                model, num_slots=num_slots, block_size=block_size,
                num_blocks=pool_blocks, prefill_chunk=chunk,
                decode_quantum=t_steps, max_context=max_ctx,
                prefix_cache=True)
            reps.append(ClusterReplica(f"r{i}", eng, policy=policy))
        return ClusterFrontDoor(ClusterRouter(
            reps, affinity_blocks=sys_blocks, strategy=strategy))

    def warm_and_reset(cfd):
        # DISTINCT random warmup prompts on every replica: compile the
        # quantum + mixed-step shapes fleet-wide without pre-seeding
        # any tenant prefix; then reset counters, caches and the
        # router's placement memory
        for rep in cfd.replicas:
            for _ in range(num_slots):
                p = wrng.randint(1, cfg.vocab_size,
                                 sys_len + tail_lo).astype(np.int32)
                rep.engine.submit(p, max_new_tokens=n_new)
            rep.engine.run()
            rep.engine.completed.clear()
            rep.engine.obs.reset()
            rep.engine.pool.clear_prefix_cache()
            rep.engine.pool._peak_blocks = \
                rep.engine.pool.blocks_in_use
        cfd.router.registry.reset()
        cfd.router._key_owner.clear()
        cfd.router._rr_next = 0

    def run_affinity_arm(strategy, n_replicas):
        cfd = mk_cluster(n_replicas, strategy, no_shed_policy())
        warm_and_reset(cfd)
        handles = [cfd.submit(p, max_new_tokens=n_new, seed=0,
                              req_id=f"q{i}")
                   for i, p in enumerate(prompts)]
        cfd.run_until_idle()
        streams = {s.request.req_id: list(s.result())
                   for s in handles}
        router = cfd.router
        cached = sum(int(r.cached_prefix_tokens)
                     for rep in cfd.replicas
                     for r in rep.engine.completed)
        pool_stats = [rep.engine.pool.prefix_cache_stats()
                      for rep in cfd.replicas]
        out = {
            "replicas": n_replicas, "strategy": strategy,
            "affinity_hit_rate": round(router._g_hit_rate.value(), 4),
            "affinity_hits": int(router._c_hits.value()),
            "keyed_requests": int(router._c_keyed.value()),
            "cached_prompt_tokens": cached,
            "prefix_hits_total": sum(s["hits"] for s in pool_stats),
            "prefix_misses_total": sum(
                s["misses"] for s in pool_stats),
        }
        log(f"  {strategy} x{n_replicas}: hit-rate "
            f"{out['affinity_hit_rate']}, cached {cached} tok")
        return out, streams

    aff4, s_aff4 = run_affinity_arm("affinity", 4)
    rr4, s_rr4 = run_affinity_arm("round_robin", 4)
    aff1, s_aff1 = run_affinity_arm("affinity", 1)
    assert s_aff4 == s_aff1 == s_rr4, (
        "cluster streams must be bit-identical across 1/4 replicas "
        "and routing strategies")

    # admitted-throughput scaling: 2 submissions per fleet pump is ~2x
    # one replica's service rate, so the single-replica cluster must
    # shed on its queue bound while the 4-replica fleet absorbs the
    # same offered trace — admission depends only on queue depths at
    # the (index-gated, not clock-gated) submission points
    scale_reqs = [rng.randint(1, cfg.vocab_size, scale_prompt)
                  .astype(np.int32) for _ in range(n_scale)]

    def run_scaling(n_replicas):
        pol = FrontDoorPolicy(max_waiting=max_wait, preempt=False)
        cfd = mk_cluster(n_replicas, "affinity", pol)
        warm_and_reset(cfd)
        admitted = 0
        for i, p in enumerate(scale_reqs):
            s = cfd.submit(p, max_new_tokens=scale_new, seed=0)
            admitted += 0 if s.shed else 1
            if i % 2 == 1:
                cfd.pump()
        cfd.run_until_idle()
        finished = sum(len(rep.engine.completed)
                       for rep in cfd.replicas)
        assert finished == admitted, (finished, admitted)
        log(f"  scaling x{n_replicas}: admitted {admitted}/{n_scale}")
        return admitted

    admitted_1 = run_scaling(1)
    admitted_4 = run_scaling(4)

    metric = "serving_cluster_affinity_hit_rate_advantage"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric,
        "value": round(aff4["affinity_hit_rate"]
                       - rr4["affinity_hit_rate"], 4),
        "unit": "hit-rate delta (affinity - round_robin, 4 replicas)",
        "admitted_scaling_1_to_4": round(
            admitted_4 / max(admitted_1, 1), 3),
        "admitted_1": admitted_1, "admitted_4": admitted_4,
        "offered_requests": n_scale,
        "cached_tokens_affinity_over_rr": round(
            aff4["cached_prompt_tokens"]
            / max(rr4["cached_prompt_tokens"], 1), 3),
        "tenants": n_tenants, "requests_per_tenant": per_tenant,
        "system_prompt_tokens": sys_len, "block_size": block_size,
        "num_slots": num_slots, "max_waiting": max_wait,
        "affinity_4": aff4, "round_robin_4": rr4, "affinity_1": aff1,
        "streams_bit_identical": True,
    }


def dispatch_decomposition():
    """ISSUE 17 acceptance row: where does a decode dispatch's wall
    time go — host-side scheduling (admission scan, table pre-growth,
    dispatch bookkeeping) vs the device program? Steady-state decode
    with all slots live, decomposed per dispatch as
    ``host_s = t_dispatch_returns - t_step_begins`` (everything before
    the jitted call is in flight) and ``device_s = wall - host_s`` (the
    async-dispatch window the collect half blocks on) — the same split
    the engine feeds the ``serving_host_gap_fraction`` gauge. Arms:
    the multi-quantum driver at K in {1, 4, 16} (one ``lax.while_loop``
    dispatch retires K quanta on-device, so the host boundary is paid
    once per K*T tokens), plus the fused online-softmax paged-attention
    inner loop at K=16 vs the XLA-gather oracle. The guarded metric is
    HOST us/token (K=16) / HOST us/token (K=1) — strictly < 1, the
    host-gap collapse the tentpole claims: one dispatch's host boundary
    amortizes over K*T tokens. The host/wall FRACTIONS ride along but
    are NOT the guard: on the CPU smoke the "device" program runs on
    the same cores and largely overlaps the host's own dispatch half
    (the async overlap working as designed), so the visible device
    window shrinks with K too and the fraction is confounded; on TPU
    device time per token is real compute and the fraction collapses
    with the per-token host cost. Every arm also replays the SAME
    ragged greedy request set closed-loop and the streams are asserted
    bit-identical across all K and both attention paths in-run (the
    on-device driver and the fused kernel change no math). Artifact
    BENCH_HOSTGAP_r18.json."""
    from paddle_tpu.serving import ServingEngine

    cfg, on_tpu = _serving_cfg()
    model = _build_model(cfg, on_tpu)
    rng = np.random.RandomState(0)
    requests = _request_set(cfg, on_tpu, rng)
    if on_tpu:
        num_slots, block_size, t_steps, chunk = 8, 32, 8, 128
        timed = 4
    else:
        num_slots, block_size, t_steps, chunk = 4, 8, 4, 8
        timed = 3
    k_max = 16
    plen = 16 if on_tpu else 8
    # steady phase: 1 warm + `timed` dispatches, each K*T tokens/slot
    steady_new = (timed + 1) * k_max * t_steps + 8
    max_ctx = max(max(p.shape[0] + n for p, n in requests),
                  plen + steady_new)
    max_ctx = -(-max_ctx // block_size) * block_size

    def run_arm(k, attn):
        eng = ServingEngine(
            model, num_slots=num_slots, block_size=block_size,
            prefill_chunk=chunk, decode_quantum=t_steps,
            max_context=max_ctx, multi_quantum=k, attn_impl=attn)
        # parity replay: the whole ragged set, closed loop, greedy
        reqs = [eng.submit(p, max_new_tokens=n) for p, n in requests]
        eng.run()
        streams = [list(map(int, eng.output_tokens(r))) for r in reqs]
        eng.obs.reset()
        # steady-state decomposition: all slots decoding, nothing
        # waiting — every dispatch runs the full K-quantum driver
        for _ in range(num_slots):
            eng.submit(rng.randint(1, cfg.vocab_size, plen)
                       .astype(np.int32), max_new_tokens=steady_new)
        while (eng.scheduler.prefilling()
               or not eng.scheduler.decoding()):
            eng.step()
        eng._decode_quantum()  # warm the K-quantum closure
        host_s = dev_s = 0.0
        toks0 = int(eng._n_gen.sum())
        t0 = time.perf_counter()
        for _ in range(timed):
            tb = time.perf_counter()
            pending = eng._decode_dispatch()
            td = time.perf_counter()  # jitted call is now in flight
            eng._decode_collect(pending)
            host_s += td - tb
            dev_s += time.perf_counter() - td
        wall = time.perf_counter() - t0
        tokens = int(eng._n_gen.sum()) - toks0
        frac = host_s / max(wall, 1e-12)
        quanta = eng.stats["decode_quanta"]
        arm = {
            "k": k, "attn": attn,
            "host_fraction": round(frac, 4),
            "host_us_per_token": round(1e6 * host_s / tokens, 2),
            "device_us_per_token": round(1e6 * dev_s / tokens, 2),
            "tokens_per_sec": round(tokens / wall, 1),
            "dispatches_timed": timed, "tokens_timed": tokens,
            "quanta_accounted": quanta,
            "host_gap_gauge": round(eng.obs.registry.get(
                "serving_host_gap_fraction").value(), 4),
        }
        log(f"  K={k:>2} {attn:>6}: host {arm['host_fraction']:.1%} "
            f"({arm['host_us_per_token']}us/tok host, "
            f"{arm['device_us_per_token']}us/tok device)")
        return arm, streams

    k1, s1 = run_arm(1, "gather")
    k4, s4 = run_arm(4, "gather")
    k16, s16 = run_arm(16, "gather")
    fused, sf = run_arm(k_max, "fused")
    assert s1 == s4 == s16 == sf, (
        "multi-quantum / fused streams must be bit-identical to the "
        "per-quantum gather driver")

    metric = "serving_hostgap_k16_over_k1_host_us_per_token"
    if not on_tpu:
        metric += "_cpu_smoke"
    return {
        "metric": metric,
        "value": round(k16["host_us_per_token"]
                       / max(k1["host_us_per_token"], 1e-9), 4),
        "unit": "x",
        "host_us_per_token_k1": k1["host_us_per_token"],
        "host_us_per_token_k4": k4["host_us_per_token"],
        "host_us_per_token_k16": k16["host_us_per_token"],
        "host_us_per_token_k16_fused": fused["host_us_per_token"],
        "host_fraction_k1": k1["host_fraction"],
        "host_fraction_k16": k16["host_fraction"],
        "fused_over_gather_tokens_per_sec": round(
            fused["tokens_per_sec"]
            / max(k16["tokens_per_sec"], 1e-9), 3),
        "fused_quantum_tokens_per_sec": fused["tokens_per_sec"],
        "decode_quantum": t_steps, "num_slots": num_slots,
        "num_requests": len(requests),
        "k1_arm": k1, "k4_arm": k4, "k16_arm": k16,
        "k16_fused_arm": fused,
        "streams_bit_identical": True,
        "hostgap_collapses": bool(
            k16["host_us_per_token"] < k1["host_us_per_token"]),
    }


CONFIGS = {
    "serving_engine": serving_engine,
    "speculative_decode": speculative_decode,
    "speculative_serving": speculative_serving,
    "serving_obs_overhead": serving_obs_overhead,
    "fault_recovery_overhead": fault_recovery_overhead,
    "attribution_overhead": attribution_overhead,
    "slo_overhead": slo_overhead,
    "serving_overload": serving_overload,
    "shared_prefix": shared_prefix,
    "serving_tp": serving_tp,
    "serving_int8": serving_int8,
    "serving_cluster": serving_cluster,
    "dispatch_decomposition": dispatch_decomposition,
}


def main():
    names = sys.argv[1:] or list(CONFIGS)
    for name in names:
        log(f"== {name} ==")
        t0 = time.perf_counter()
        try:
            out = CONFIGS[name]()
            out["wall_s"] = round(time.perf_counter() - t0, 1)
            print(json.dumps(out), flush=True)
        except Exception as e:
            print(json.dumps(
                {"metric": name,
                 "error": f"{type(e).__name__}: {e}"[:200]}),
                flush=True)


if __name__ == "__main__":
    main()
