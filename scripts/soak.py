"""Seeded chaos soak CLI (ISSUE 13 acceptance driver).

    PYTHONPATH=. JAX_PLATFORMS=cpu python scripts/soak.py \
        --rounds 200 --seed 0 [--spec]

Thin wrapper over :func:`paddle_tpu.serving.soak.run_soak` — two
engines on the same seeded workload, faults x preempt x COW (plus the
speculative round with ``--spec``), hard-asserting that every
non-poisoned stream is bit-exact vs the fault-free arm and nothing
leaks. Prints the JSON report; any failure replays from ``--seed``
alone. Budget note: the eager mixed-prefill step dominates on CPU
(~2 s/step), so 200 rounds run ~8 minutes.
"""
import argparse
import json
import sys
import time


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--spec", action="store_true",
                    help="speculative arm (draft model + spec faults)")
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving.soak import run_soak

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    model.eval()
    draft = None
    if args.spec:
        paddle.seed(11)
        draft = LlamaForCausalLM(
            LlamaConfig.tiny(tensor_parallel=False,
                             num_hidden_layers=1))
        draft.eval()
    t0 = time.time()
    report = run_soak(model, spec_draft=draft, rounds=args.rounds,
                      seed=args.seed)
    report["elapsed_s"] = round(time.time() - t0, 1)
    json.dump(report, sys.stdout, indent=2, sort_keys=True)
    print()
    print(f"soak OK: {report['rounds']} rounds, "
          f"{report['requests']} requests, "
          f"{report['faults_injected']} faults injected, "
          f"{report['bitexact_streams']} bit-exact streams",
          file=sys.stderr)


if __name__ == "__main__":
    main()
