"""Cost-model bench (ISSUE 16): predicted roofline floor vs measured
dispatch wall for the single-chip audited recipes.

    JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/bench_cost.py

For each single-chip recipe the static cost model (analysis/cost.py)
predicts the DEVICE-TIME FLOOR on the default chip spec —
``max(flops/peak, bytes/bw)`` from the jaxpr-walked FLOP/byte counts —
and the bench measures the actual per-dispatch wall in-process
(warmup + timed iterations, ``block_until_ready``; buffer donation is
not enforced on the CPU backend, so re-dispatching the same args is
sound for timing). The HOST GAP column (wall - floor) is a CPU wall
against a TPU-spec floor: an upper bound on the dispatch overhead a
device run could hide behind, NOT a TPU claim — the floors become
testable on hardware, the agreement ratio is testable everywhere.

One extra row pins the CROSS-SOURCE AGREEMENT on the serving decode
quantum — static jaxpr flops over XLA ``cost_analysis()`` flops — the
ratio the `--cost` CLI gates per-recipe and perf budget
``cost-cross-source-agreement`` guards in BENCH_COST_r17.json
(backend-independent: the walker counts the traced program, so the
ratio moves only when the graph or the walker changes).

The mesh recipes (tp2 x zero4 train, tp2 serving) are audited by
`--cost` but not timed here: their 8-virtual-device dispatch walls on
one CPU measure contention, not dispatch overhead.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu import analysis  # noqa: E402
from paddle_tpu.analysis.cost import DEFAULT_CHIP, roofline  # noqa: E402

#: recipes timed here: single-chip quanta whose dispatch wall on one
#: CPU is a meaningful (if noisy) per-dispatch overhead measurement
TIMED_RECIPES = (
    "llama_decode_greedy",
    "serving_decode_step",
    "speculative_verify_step",
    "serving_int8_step",
)

WARMUP = 2
ITERS = 10


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _time_dispatch(target, args):
    """Median per-dispatch wall seconds over ITERS timed calls.

    The quanta donate their leading pool args, so every call consumes
    its inputs — snapshot the example args to host ONCE, then upload a
    fresh device copy per call OUTSIDE the timed window (the timed
    region is dispatch + compute only, matching what the roofline
    floor models)."""
    import numpy as np

    snapshot = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x,
        args)

    def fresh():
        a = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray)
            else x, snapshot)
        jax.block_until_ready(a)
        return a

    for _ in range(WARMUP):
        jax.block_until_ready(target(*fresh()))
    walls = []
    for _ in range(ITERS):
        a = fresh()
        t0 = time.perf_counter()
        jax.block_until_ready(target(*a))
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


def _recipe_row(name, chip=DEFAULT_CHIP):
    recipe = analysis.build_recipe(name)
    try:
        report = recipe.audit()
        c = report.cost
        rl = roofline(c.flops, c.bytes_accessed, chip=chip)
        wall = _time_dispatch(recipe.target, recipe.args)
    finally:
        recipe.close()
    floor_us = rl.device_floor_s * 1e6
    wall_us = wall * 1e6
    return {
        "metric": "cost_model_floor_vs_measured_cpu_smoke",
        "recipe": name,
        "value": round(wall_us / floor_us, 1),
        "unit": f"measured cpu wall / {rl.chip.name} floor "
                f"(dispatch-overhead upper bound, not a TPU claim)",
        "measured_us_per_dispatch": round(wall_us, 1),
        "predicted_floor_us": round(floor_us, 2),
        "host_gap_us_upper_bound": round(wall_us - floor_us, 1),
        "chip": rl.chip.name,
        "bound": rl.bound,
        "arithmetic_intensity": round(rl.intensity, 3),
        "flops_per_dispatch": c.flops,
        "hbm_bytes_per_dispatch": c.bytes_accessed,
        "cost_source": c.source,
        "flops_ratio_jaxpr_over_xla": (
            round(c.flops_ratio, 3) if c.flops_ratio else None),
        "warmup": WARMUP, "iters": ITERS,
    }


def _agreement_row():
    recipe = analysis.build_recipe("serving_decode_step")
    try:
        c = recipe.audit().cost
    finally:
        recipe.close()
    return {
        "metric": "cost_model_cross_source_agreement_cpu_smoke",
        "value": round(c.flops_ratio, 3),
        "unit": "jaxpr-static flops / xla cost_analysis flops "
                "(serving decode quantum)",
        "recipe": "serving_decode_step",
        "band_lo": analysis.AGREEMENT_BAND[0],
        "band_hi": analysis.AGREEMENT_BAND[1],
        "n_partitions": c.n_partitions,
    }


def cost_rows():
    rows = []
    for name in TIMED_RECIPES:
        log(f"  timing {name} ...")
        rows.append(_recipe_row(name))
    rows.append(_agreement_row())
    return rows


def cost_model():
    """bench_suite entry: the guarded agreement row, with the per-
    recipe floor-vs-measured summary folded in as extra fields."""
    rows = cost_rows()
    head = rows[-1]
    for r in rows[:-1]:
        key = r["recipe"]
        head[f"{key}_measured_us"] = r["measured_us_per_dispatch"]
        head[f"{key}_floor_us"] = r["predicted_floor_us"]
    return head


def main():
    out = {
        "round": "PR17",
        "cmd": "JAX_PLATFORMS=cpu PYTHONPATH=. python "
               "scripts/bench_cost.py",
        "device": "cpu (JAX_PLATFORMS=cpu smoke; floors are "
                  f"{DEFAULT_CHIP} TPU-spec predictions — the "
                  "wall/floor ratio is a dispatch-overhead upper "
                  "bound, the agreement ratio is backend-independent)",
        "note": "Static cost model & roofline sentinel (ISSUE 16): "
                "jaxpr-walked FLOP/byte counts cross-checked against "
                "XLA cost_analysis, device-time floors from the chip "
                "spec table, measured single-chip dispatch walls for "
                "the host-gap column of `python -m paddle_tpu."
                "analysis --cost`. See BENCH_NOTES.md cost section.",
        "rows": cost_rows(),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_COST_r17.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=False)
        f.write("\n")
    log(f"wrote {path}")
    print(json.dumps(out["rows"][-1]))


if __name__ == "__main__":
    main()
