"""Pipeline-parallel overlap measurement on the virtual device mesh.

Evidence target (round-1 verdict): with m microbatches and S stages, a
pipelined step should take less than m * (sum of per-stage times) —
i.e. the schedule actually overlaps stage compute across microbatches.

Run: python scripts/bench_pp.py  (forces an 8-device CPU mesh)
"""
import json
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402


def main():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineParallel,
    )
    from paddle_tpu.parallel import mesh as mesh_state

    H = 1024
    S, M = 4, 16  # stages, microbatches

    def descs():
        out = []
        for _ in range(8):
            out.append(LayerDesc(nn.Linear, H, H))
            out.append(LayerDesc(nn.ReLU))
        out.append(LayerDesc(nn.Linear, H, 16))
        return out

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": S, "sharding_degree": 1,
    }
    strategy.pipeline_configs = {"accumulate_steps": M}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    pipe = PipelineLayer(layers=descs(), num_stages=S,
                         loss_fn=nn.CrossEntropyLoss())
    model = PipelineParallel(pipe, fleet.get_hybrid_communicate_group(),
                             strategy)
    opt = paddle.optimizer.SGD(0.01, parameters=pipe.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(M * 8, H).astype(np.float32))
    y = paddle.to_tensor((np.arange(M * 8) % 16).astype(np.int64))

    # warm up / compile
    model.train_batch([x, y], opt)
    t0 = time.perf_counter()
    for _ in range(3):
        model.train_batch([x, y], opt)
    pipelined = (time.perf_counter() - t0) / 3

    # per-microbatch serial chain cost: engine with ONE microbatch
    strategy.pipeline_configs = {"accumulate_steps": 1}
    model2 = PipelineParallel(pipe, fleet.get_hybrid_communicate_group(),
                              strategy)
    xm = paddle.to_tensor(np.random.RandomState(0).randn(8, H).astype(np.float32))
    ym = paddle.to_tensor((np.arange(8) % 16).astype(np.int64))
    model2.train_batch([xm, ym], opt)
    t0 = time.perf_counter()
    for _ in range(3):
        model2.train_batch([xm, ym], opt)
    single = (time.perf_counter() - t0) / 3

    serial_estimate = single * M
    overlap = serial_estimate / pipelined if pipelined > 0 else 0
    print(f"pipelined step (M={M}): {pipelined*1e3:.1f} ms; "
          f"1-micro step: {single*1e3:.1f} ms; serial estimate "
          f"{serial_estimate*1e3:.1f} ms", file=sys.stderr)
    print(json.dumps({
        "metric": "pp4_overlap_speedup",
        "value": round(overlap, 3),
        "unit": "x (serial_estimate / pipelined)",
        "pipelined_ms": round(pipelined * 1e3, 1),
        "serial_estimate_ms": round(serial_estimate * 1e3, 1),
    }))
    mesh_state.set_mesh(None)


if __name__ == "__main__":
    main()
