"""Driver benchmark: single-chip Llama-block pretrain step under the
fully-jitted path (bf16 params + f32 master weights + bf16 Adam moments,
Pallas flash attention, no activation recompute), reporting MFU against
the BASELINE.md north-star (45% MFU).

Prints ONE JSON line to stdout; human detail goes to stderr.
"""
from __future__ import annotations

import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_step(cfg, batch, seq, lr=1e-4, moment_dtype="float32"):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaForCausalLM, LlamaPretrainingCriterion
    from paddle_tpu.jit.train import JittedTrainStep

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.astype("bfloat16")
    fused = getattr(cfg, "fuse_linear_cross_entropy", False)
    crit = LlamaPretrainingCriterion(
        cfg, lm_head=model.lm_head if fused else None)

    if fused:
        # chunked fused lm-head+CE: model returns bf16 hidden; the op
        # accumulates in f32 — no full logits buffer ever exists
        def criterion(out, labels):
            return crit(out, labels)
    else:
        def criterion(out, labels):
            return crit(out.astype("float32"), labels)

    opt = paddle.optimizer.AdamW(
        lr, parameters=model.parameters(), weight_decay=0.01,
        multi_precision=True, moment_dtype=moment_dtype,
    )
    step = JittedTrainStep(model, criterion, opt)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, seq))
    )
    return model, step, ids


def count_params(model):
    return sum(
        int(np.prod(p._value.shape))
        for _, p in model.named_parameters()
        for np in [__import__("numpy")]
    )


def main():
    import jax

    backend = jax.default_backend()
    dev = jax.devices()[0]
    log(f"backend={backend} device={dev.device_kind} n={len(jax.devices())}")

    from paddle_tpu.nlp import LlamaConfig
    from paddle_tpu.profiler.mfu import (
        MFUMeter, transformer_train_flops, peak_flops_per_chip,
    )

    on_tpu = backend == "tpu"
    if on_tpu:
        # END-TO-END training at Llama-2-7B dimensions (BASELINE config
        # #3: h4096/d128/inter11008/vocab32000) — L=4 layers of exactly
        # the 7B shape fit one v5e-16G (~1.07B params; bf16 params + f32
        # master + bf16 Adam moments). Measured sweep (round 4,
        # BENCH_NOTES): B1 S4096 no-remat 70.1% MFU beats B2 (61.6%,
        # HBM pressure) and B2+attn-remat (61.5%). The earlier 941M
        # h2048 headline (47.7%, shape-bound at d=64) lives on as a
        # bench_suite row.
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_hidden_layers=4, num_attention_heads=32,
            max_position_embeddings=4096, tensor_parallel=False,
            use_recompute=False,
        )
        batch, seq, iters = 1, 4096, 3
    else:  # CPU smoke path so the bench never hard-fails off-TPU
        cfg = LlamaConfig.tiny(tensor_parallel=False)
        batch, seq, iters = 2, 64, 2

    import numpy as np
    import paddle_tpu as paddle

    K = 10 if on_tpu else 2  # train steps fused into one dispatch
    # OOM fallback ladder covers build AND first execution (compilation
    # is lazy — activation OOM surfaces inside meter.measure, not
    # build_step): full config → seq 2048 → attention remat.
    for attempt in range(3):
        try:
            model, step, ids = build_step(
                cfg, batch, seq,
                moment_dtype="bfloat16" if on_tpu else "float32")
            n_params = count_params(model)
            tokens = batch * seq
            flops = transformer_train_flops(
                n_params, tokens, num_layers=cfg.num_hidden_layers,
                seq_len=seq, hidden=cfg.hidden_size, causal=True,
            )
            log(f"params={n_params/1e6:.1f}M tokens/step={tokens} K={K} "
                f"steps/dispatch model TFLOPs/step={flops/1e12:.2f} "
                f"peak={peak_flops_per_chip()/1e12:.0f}")

            # K different batches stacked along a leading scan dim
            ids_stacked = paddle.to_tensor(np.random.RandomState(1).randint(
                0, cfg.vocab_size, (K, batch, seq)))

            t0 = time.perf_counter()
            meter = MFUMeter(flops * K, tokens * K)
            res = meter.measure(
                lambda: step.run_steps(ids_stacked, ids_stacked),
                warmup=1, iters=iters)
            break
        except Exception as e:  # OOM → shorter sequence, then remat
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            # the failed attempt's params/master/moments (~10GB) must be
            # freed BEFORE the retry builds its own, or the retry OOMs too
            model = step = ids_stacked = meter = None
            if seq > 2048:
                log(f"OOM at seq={seq}; halving ({e.__class__.__name__})")
                seq //= 2
            elif not cfg.use_recompute:
                log("OOM; enabling attention recompute")
                cfg.use_recompute = True
                cfg.recompute_granularity = "core_attn"
            else:
                raise
    # meter timed K-step dispatches; rescale to per-step
    res["step_time_s"] /= K
    log(f"compile+warmup+{iters}x{K}-step dispatches took "
        f"{time.perf_counter()-t0:.1f}s")
    log(json.dumps(res, indent=2))

    mfu = res.get("mfu")
    if mfu:
        out = {
            "metric": "llama_7b_shape_e2e_train_mfu",
            "value": round(mfu * 100, 2),
            "unit": "%MFU",
            "vs_baseline": round(mfu / 0.45, 3),
            "tokens_per_sec_per_chip": round(res["tokens_per_sec_per_chip"]),
            "device": dev.device_kind,
            # config actually measured (differs from headline after an
            # OOM fallback — comparable only same-config)
            "seq": seq,
            "remat": bool(cfg.use_recompute),
        }
    else:  # unknown peak (CPU smoke) — report throughput
        out = {
            "metric": "llama_tiny_train_tokens_per_sec",
            "value": round(res["tokens_per_sec"], 1),
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "device": dev.device_kind,
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
